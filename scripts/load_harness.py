#!/usr/bin/env python
"""Closed-loop multi-tenant load harness: prove overload survival.

``bench_serve`` is open-loop and single-tenant — it measures the happy
path. This harness measures the regime the ROADMAP's "millions of
users" pillar actually lives in: **sustained offered load beyond
capacity**, with tenants that do not cooperate. It stands up the REAL
stack (fitted PCA model → registry → engine with quotas + weighted-fair
scheduling + adaptive shedding → stdlib HTTP server) and drives it from
closed-loop client threads over the wire:

1. **calibrate** — one well-behaved tenant, closed loop, measures
   single-tenant capacity (rows/sec at the configured concurrency);
2. **overload soak** (``SPARKML_LOAD_SOAK_SECONDS``, default 60) — two
   tenants at once:

   * ``compliant`` — interactive priority, paced (Poisson think time)
     at ~25% of capacity, inside its 30% quota: the tenant the
     fairness contract protects;
   * ``greedy`` — batch priority, zero think time from
     ``SPARKML_LOAD_GREEDY_THREADS`` closed-loop threads, quota 45% of
     capacity, request size AUTO-SCALED from calibration so its flood
     pushes TOTAL offered load past 2× capacity — everything beyond
     its quota is the over-quota excess the controller sheds. (The
     quota split is work-conserving: in-quota greedy + compliant
     traffic together carry near-capacity throughput while the excess
     absorbs every rejection. The 10×-over-quota starvation case lives
     in tests/test_serve_fairness.py with an injected clock.)

The robustness acceptance judged on the emitted record:

* compliant availability ≥ ``SPARKML_LOAD_MIN_AVAILABILITY`` (0.99) and
  compliant p99 within its SLO (``SPARKML_LOAD_P99_MS``, default the
  serve latency SLO threshold) — the greedy flood cannot starve the
  in-SLO tenant;
* total served throughput ≥ ``SPARKML_LOAD_THROUGHPUT_FRACTION`` (0.9)
  × calibrated capacity — shedding sheds *excess*, not *capacity*;
* every circuit breaker CLOSED at the end — overload must never read
  as backend failure (the PR 6 invariant, extended);
* the shedding lands on the greedy tenant (its availability and shed
  counts are in the record; the compliant tenant's sheds must be 0).

Emits ONE ``bench_common.emit_record`` line the perf sentinel judges
(metric ``load_harness_compliant_availability``, explicitly
higher-is-better) — committed history lives in
``records/load_harness_r*.json``. Exit 0 = all gates pass.

Knobs (env): SPARKML_LOAD_SOAK_SECONDS (60),
SPARKML_LOAD_CALIBRATE_SECONDS (8), SPARKML_LOAD_FEATURES (32),
SPARKML_LOAD_K (8), SPARKML_LOAD_GREEDY_THREADS (12),
SPARKML_LOAD_COMPLIANT_THREADS (4), SPARKML_LOAD_MIN_AVAILABILITY
(0.99), SPARKML_LOAD_THROUGHPUT_FRACTION (0.9), SPARKML_LOAD_P99_MS
(the SLO threshold), plus every SPARK_RAPIDS_ML_TPU_SERVE_* engine knob.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

# The overload soak WILL open SLO-burn incidents (that is the point) —
# but an incident-triggered jax profile capture mid-soak would measure
# the profiler, not the scheduler (start_trace wedges on this
# container's CPU backend under live traffic — the PR 7 lesson). Set
# BEFORE the package import, like the chaos drill.
os.environ.setdefault("SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_CAPTURE_S", "0")

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench_common  # noqa: E402 (scripts/ on path when run directly)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _post_predict(base: str, body: bytes, tenant: str, priority: str,
                  timeout: float = 30.0):
    """One HTTP predict; (status, retry_after_s, shed). Never raises.

    Tenant/priority ride the HEADERS (as well as the body) so the
    server's pre-parse fast-shed path can identify the request class
    without touching the payload."""
    req = urllib.request.Request(
        f"{base}/predict", data=body,
        headers={"Content-Type": "application/json",
                 "X-Tenant": tenant, "X-Priority": priority},
    )
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        resp.read()
        return resp.status, None, False
    except urllib.error.HTTPError as exc:
        retry_after = exc.headers.get("Retry-After")
        try:
            payload = json.loads(exc.read())
        except ValueError:
            payload = {}
        return (exc.code,
                float(retry_after) if retry_after else None,
                bool(payload.get("shed")))
    except Exception:  # noqa: BLE001 - a hang/reset IS the measurement
        return 0, None, False


class TenantLoad:
    """One tenant's closed-loop client fleet.

    Each thread loops: think (exponential, ``pace_rps`` per thread; 0 =
    no think time — pure closed loop), pick a request size, POST, record
    (status, latency, rows, shed). ``stop_at`` ends the phase."""

    def __init__(self, base: str, model: str, x: np.ndarray, *,
                 tenant: str, priority: str, threads: int,
                 pace_rps_per_thread: float, rows_lo: int, rows_hi: int,
                 reject_pause_s: float = 0.01,
                 deadline_ms: float = 0.0, seed: int = 0):
        self.base = base
        self.model = model
        self.x = x
        self.tenant = tenant
        self.priority = priority
        self.threads = threads
        self.pace = pace_rps_per_thread
        self.rows_lo, self.rows_hi = rows_lo, rows_hi
        self.reject_pause_s = reject_pause_s
        self.deadline_ms = deadline_ms
        self.seed = seed
        self.lock = threading.Lock()
        self.results = []  # (status, latency_s, rows, shed)

    def _client(self, idx: int, stop_at: float) -> None:
        rng = np.random.default_rng(self.seed * 1000 + idx)
        while time.monotonic() < stop_at:
            if self.pace > 0:
                think = float(rng.exponential(1.0 / self.pace))
                if time.monotonic() + think >= stop_at:
                    return
                time.sleep(think)
            n = int(rng.integers(self.rows_lo, self.rows_hi + 1))
            start = int(rng.integers(0, self.x.shape[0] - n))
            payload = {
                "model": self.model,
                "rows": self.x[start:start + n].tolist(),
                "tenant": self.tenant,
                "priority": self.priority,
            }
            if self.deadline_ms > 0:
                payload["deadline_ms"] = self.deadline_ms
            body = json.dumps(payload).encode()
            t0 = time.perf_counter()
            status, _retry_after, shed = _post_predict(
                self.base, body, self.tenant, self.priority)
            latency = time.perf_counter() - t0
            with self.lock:
                self.results.append((status, latency, n, shed))
            if status != 200 and self.reject_pause_s > 0:
                # a rejected closed-loop client spinning at MHz would
                # measure the client, not the server — tiny pause only
                time.sleep(self.reject_pause_s)

    def run(self, seconds: float) -> None:
        stop_at = time.monotonic() + seconds
        workers = [
            threading.Thread(target=self._client, args=(i, stop_at),
                             daemon=True)
            for i in range(self.threads)
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join(seconds + 60.0)

    def stats(self, wall: float) -> dict:
        with self.lock:
            results = list(self.results)
        attempts = len(results)
        ok = [(lat, n) for s, lat, n, _ in results if s == 200]
        lat_ok = sorted(lat for lat, _n in ok)
        served_rows = sum(n for _lat, n in ok)

        def pct(q: float) -> float:
            if not lat_ok:
                return 0.0
            return lat_ok[min(int(q * len(lat_ok)), len(lat_ok) - 1)]

        return {
            "tenant": self.tenant,
            "priority": self.priority,
            "threads": self.threads,
            "attempts": attempts,
            "ok": len(ok),
            "availability": len(ok) / attempts if attempts else 0.0,
            "shed": sum(1 for s, _l, _n, shed in results
                        if shed and s != 200),
            "rejected_429": sum(1 for s, *_ in results if s == 429),
            "status_5xx": sum(1 for s, *_ in results
                              if 500 <= s <= 599),
            "timeouts_504": sum(1 for s, *_ in results if s == 504),
            "hung": sum(1 for s, *_ in results if s == 0),
            "offered_rps": attempts / wall if wall > 0 else 0.0,
            "offered_rows_per_sec": (sum(n for _s, _l, n, _ in results)
                                     / wall if wall > 0 else 0.0),
            "served_rows_per_sec": (served_rows / wall
                                    if wall > 0 else 0.0),
            "p50": pct(0.50),
            "p99": pct(0.99),
        }


def _get_json(base: str, path: str) -> dict:
    try:
        resp = urllib.request.urlopen(f"{base}{path}", timeout=10.0)
        return json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        try:
            return json.loads(exc.read())
        except ValueError:
            return {}
    except Exception:  # noqa: BLE001 - a dead ops endpoint IS a finding
        return {}


DEVICE_CHILD_PREFIX = "DEVICE_CAPACITY_RESULT "


def device_capacity_child() -> int:
    """One device count's capacity calibration (run in its own process
    — device count is fixed at jax init): a closed-loop single-tenant
    load over the REAL HTTP server with a modeled per-batch device
    service time (``SPARKML_LOAD_DEVICE_MS``, default 40 — a GIL-
    released latency fault at every replica dispatch, same CPU-CI
    honesty note as ``bench_serve``'s multidevice scenario: a 1-core
    container cannot show FLOPS parallelism, so the phase judges the
    TIER's capacity scaling; set 0 on real hardware)."""
    import jax

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        ServeEngine,
        fault_plane,
        start_serve_server,
    )

    seconds = _env_float("SPARKML_LOAD_DEVICE_SECONDS", 8.0)
    device_ms = _env_float("SPARKML_LOAD_DEVICE_MS", 40.0)
    n_features = _env_int("SPARKML_LOAD_FEATURES", 16)
    k = _env_int("SPARKML_LOAD_K", 8)
    rng = np.random.default_rng(23)
    x = rng.normal(size=(2048, n_features))
    model = PCA().setK(k).fit(x)
    registry = ModelRegistry()
    registry.register("load_md_pca", model)
    engine = ServeEngine(registry, max_batch_rows=256, max_wait_ms=2.0,
                         max_queue_depth=256)
    engine.warmup("load_md_pca")
    if device_ms > 0:
        fault_plane().inject("load_md_pca", "latency", count=None,
                             seconds=device_ms / 1000.0)
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    # full-bucket requests: one request = one modeled device dispatch,
    # so measured capacity is the tier's dispatch concurrency (see the
    # bench_serve multidevice rationale)
    load = TenantLoad(base, "load_md_pca", x, tenant="calibrate",
                      priority="interactive", threads=12,
                      pace_rps_per_thread=0.0, rows_lo=256, rows_hi=256,
                      seed=5)
    t0 = time.monotonic()
    load.run(seconds)
    wall = time.monotonic() - t0
    stats = load.stats(wall)
    server.shutdown()
    engine.shutdown()
    from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

    tsdb_mod.get_sampler().stop()
    time.sleep(1.0)
    result = {
        "devices": len(jax.devices()),
        "modeled_device_ms": device_ms,
        "seconds": wall,
        "capacity_rows_per_sec": stats["served_rows_per_sec"],
        "availability": stats["availability"],
        "p50_ms": stats["p50"] * 1000.0,
        "p99_ms": stats["p99"] * 1000.0,
        "hung": stats["hung"],
    }
    sys.stdout.write(DEVICE_CHILD_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0


def run_device_scaling_phase() -> dict:
    """Capacity at 1 vs 2 devices, each in its own subprocess: the
    device-scaling gate — 2-device capacity must be >= 1.6x the
    1-device calibration with compliant p99 under the single-device
    bar."""
    import subprocess

    results = {}
    for n in (1, 2):
        env = dict(os.environ)
        env["SPARKML_LOAD_PHASE"] = "device_capacity_child"
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
        env["XLA_FLAGS"] = bench_common.force_device_count_flags(n)
        env.pop("SPARK_RAPIDS_ML_TPU_SERVE_REPLICAS", None)
        bench_common.log(f"load_harness device scaling: child at "
                         f"{n} device(s)")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=420,
        )
        result = bench_common.prefixed_result(proc.stdout,
                                              DEVICE_CHILD_PREFIX)
        if result is None:
            return {"error": f"device child at {n} produced no result "
                             f"(rc={proc.returncode}): "
                             f"{proc.stderr[-1500:]}"}
        results[n] = result
    base_cap = results[1]["capacity_rows_per_sec"]
    ratio = (results[2]["capacity_rows_per_sec"] / base_cap
             if base_cap else 0.0)
    # the single-device bar: the same derivation the soak uses — the
    # SLO latency threshold or 2x the single-device tail, whichever is
    # looser (adding a device must not make the protected tail worse)
    bar_ms = max(
        _env_float("SPARK_RAPIDS_ML_TPU_SLO_LATENCY_THRESHOLD_MS",
                   250.0),
        2.0 * results[1]["p99_ms"])
    return {
        "one_device": results[1],
        "two_devices": results[2],
        "capacity_ratio": ratio,
        "p99_bar_ms": bar_ms,
        "p99_under_bar": results[2]["p99_ms"] <= bar_ms,
    }


RAMP_CHILD_PREFIX = "RAMP_CHILD_RESULT "


def ramp_child() -> int:
    """The autoscale ramp phase (own process — forced 4 host devices):
    offered load ramps 1× → 3× → 1× of single-replica capacity while an
    ``AutoscaleController`` moves the replica count against the live
    queue-wait/shed/burn/occupancy signals. Modeled per-batch device
    service time (``SPARKML_LOAD_RAMP_DEVICE_MS``, default 40 — the
    same CPU-CI honesty device as the other multi-device phases) makes
    capacity replica-bound, so the controller's decisions are the
    thing under test, not this container's FLOPS."""
    import json

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        AutoscaleController,
        ModelRegistry,
        ServeEngine,
        fault_plane,
        start_serve_server,
    )

    seg_s = _env_float("SPARKML_LOAD_RAMP_SEGMENT_S", 12.0)
    down_s = _env_float("SPARKML_LOAD_RAMP_DOWN_S", 18.0)
    device_ms = _env_float("SPARKML_LOAD_RAMP_DEVICE_MS", 40.0)
    unit_rps = _env_float("SPARKML_LOAD_RAMP_UNIT_RPS", 12.0)
    n_features = _env_int("SPARKML_LOAD_FEATURES", 16)
    k = _env_int("SPARKML_LOAD_K", 8)
    rng = np.random.default_rng(29)
    x = rng.normal(size=(2048, n_features))
    model = PCA().setK(k).fit(x)
    registry = ModelRegistry()
    registry.register("ramp_pca", model)
    engine = ServeEngine(registry, max_batch_rows=256, max_wait_ms=2.0,
                         max_queue_depth=512)
    # warm the FULL ladder at full scale first (on a real deploy the
    # persistent executable cache makes this a disk replay), then start
    # scaled down to min — scale-up must be cheap because warm
    engine.warmup("ramp_pca")
    engine.scale_replicas(1)
    if device_ms > 0:
        fault_plane().inject("ramp_pca", "latency", count=None,
                             seconds=device_ms / 1000.0)
    controller = AutoscaleController(
        engine, min_replicas=1, max_replicas=4, interval_s=0.25,
        up_queue_wait_s=0.06, up_hold_s=0.5, down_hold_s=3.0,
        cooldown_s=1.5, down_queue_wait_s=0.02, down_occupancy=0.55,
        up_occupancy=0.9,
    )
    controller.start()
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    # replica-count trajectory watcher (0.25 s cadence)
    trajectory = []
    stop_watch = threading.Event()

    def _watch() -> None:
        t_start = time.monotonic()
        while not stop_watch.is_set():
            trajectory.append((time.monotonic() - t_start,
                               engine.replica_scale()))
            time.sleep(0.25)

    watcher = threading.Thread(target=_watch, daemon=True)
    watcher.start()

    segments = []
    threads = 8
    for name, mult, seconds in (("ramp_1x_a", 1.0, seg_s),
                                ("ramp_3x", 3.0, seg_s),
                                ("ramp_1x_b", 1.0, down_s)):
        rate = unit_rps * mult
        load = TenantLoad(base, "ramp_pca", x, tenant="ramp",
                          priority="interactive", threads=threads,
                          pace_rps_per_thread=rate / threads,
                          rows_lo=256, rows_hi=256, seed=11)
        t0 = time.monotonic()
        load.run(seconds)
        wall = time.monotonic() - t0
        stats = load.stats(wall)
        # steady-state tail: drop the adaptation window after each
        # transition (the controller needs hold+cooldown to converge;
        # the phase judges the CONVERGED posture, spikes are the
        # signal that drives it)
        adapt_s = _env_float("SPARKML_LOAD_RAMP_ADAPT_S", 5.0)
        with load.lock:
            results = list(load.results)
        # results are appended in completion order; approximate the
        # adaptation cut by request count at the offered rate — but
        # never cut past what actually completed: a throughput
        # collapse must not empty the window and read as a 0.0 p99
        # (the gate would pass vacuously on the exact regression it
        # exists to catch). Fewer results than the nominal skip means
        # the "steady state" never arrived — judge the WHOLE segment.
        skip = min(int(rate * adapt_s), max(len(results) // 2, 0))
        steady = sorted(lat for s, lat, _n, _shed in results[skip:]
                        if s == 200)
        stats["steady_p99"] = (
            steady[min(int(0.99 * len(steady)), len(steady) - 1)]
            if steady else stats["p99"] or float("inf"))
        stats["segment"] = name
        stats["offered_mult"] = mult
        stats["replicas_at_end"] = engine.replica_scale()
        segments.append(stats)
    # let the down-scale hysteresis finish before the final reading
    settle_s = _env_float("SPARKML_LOAD_RAMP_SETTLE_S", 8.0)
    time.sleep(settle_s)
    stop_watch.set()
    watcher.join(2.0)
    controller.stop()
    breakers = engine.breaker_snapshot()
    history = controller.decision_history()
    snapshot = controller.snapshot()
    server.shutdown()
    engine.shutdown()
    from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

    tsdb_mod.get_sampler().stop()
    time.sleep(1.0)
    replica_counts = [r for _t, r in trajectory]
    actions = [h for h in history
               if h["decision"] in ("scale_up", "scale_down")]
    action_gaps = [round(b["at"] - a["at"], 3)
                   for a, b in zip(actions, actions[1:])]
    result = {
        "devices": 4,
        "modeled_device_ms": device_ms,
        "unit_rps": unit_rps,
        "segments": segments,
        "replicas_max": max(replica_counts, default=1),
        "replicas_end": engine.replica_scale(),
        "replica_trajectory": replica_counts,
        "scale_actions": [
            {"decision": h["decision"], "from": h["from"],
             "to": h["to"]} for h in actions],
        "action_gaps_s": action_gaps,
        "cooldown_s": controller.cooldown_s,
        "breakers_closed": all(b["state"] == "closed"
                               for b in breakers.values()),
        "autoscale_snapshot": {
            "min": snapshot["min"], "max": snapshot["max"],
            "signals": snapshot["signals"],
        },
    }
    sys.stdout.write(RAMP_CHILD_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0


def run_ramp_phase() -> int:
    """Parent leg of the autoscale ramp phase: spawn the 4-device child,
    judge the gates, emit the sentinel record. Gates:

    * replica count RISES on the up-ramp (max ≥ 2) and RETIRES back to
      the floor on the down-ramp (end == 1);
    * compliant availability ≥ ``SPARKML_LOAD_MIN_AVAILABILITY`` (0.99)
      in every segment, steady-state p99 under the bar throughout;
    * no two scale actions closer than the hysteresis cooldown (the
      anti-flap contract);
    * every circuit breaker CLOSED (elasticity must never read as
      backend failure)."""
    import subprocess

    env = dict(os.environ)
    env["SPARKML_LOAD_PHASE"] = "ramp_child"
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["XLA_FLAGS"] = bench_common.force_device_count_flags(4)
    env.pop("SPARK_RAPIDS_ML_TPU_SERVE_REPLICAS", None)
    bench_common.log("load_harness ramp: child at 4 device(s), "
                     "1x -> 3x -> 1x offered")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=600,
    )
    result = bench_common.prefixed_result(proc.stdout, RAMP_CHILD_PREFIX)
    if result is None:
        bench_common.log(
            f"load_harness ramp FAIL: child produced no result "
            f"(rc={proc.returncode}): {proc.stderr[-2000:]}")
        return 1
    min_availability = _env_float("SPARKML_LOAD_MIN_AVAILABILITY", 0.99)
    p99_bar_ms = _env_float(
        "SPARKML_LOAD_RAMP_P99_MS",
        max(_env_float("SPARK_RAPIDS_ML_TPU_SLO_LATENCY_THRESHOLD_MS",
                       250.0),
            8.0 * result["modeled_device_ms"]))
    availability = min(
        (s["availability"] for s in result["segments"]), default=0.0)
    worst_steady_p99_ms = max(
        (s["steady_p99"] * 1000.0 for s in result["segments"]),
        default=0.0)
    record = {
        "bench": "load_harness_ramp",
        "metric": "load_harness_ramp_availability",
        "value": availability,
        "unit": ("worst per-segment availability through a 1x->3x->1x "
                 "offered-load ramp under the autoscale controller"),
        "higher_is_better": True,
        "platform": "cpu",
        "device_kind": "cpu",
        **{k: v for k, v in result.items()
           if k != "replica_trajectory"},
        "worst_steady_p99_ms": worst_steady_p99_ms,
        "p99_bar_ms": p99_bar_ms,
    }
    bench_common.emit_record(record, include_metrics=False)
    failures = []
    if result["replicas_max"] < 2:
        failures.append(
            f"replica count never rose above "
            f"{result['replicas_max']} on the 3x up-ramp")
    if result["replicas_end"] != 1:
        failures.append(
            f"replica count ended at {result['replicas_end']}, not "
            "retired back to the 1-replica floor")
    if availability < min_availability:
        failures.append(
            f"availability {availability:.4f} < {min_availability}")
    if worst_steady_p99_ms > p99_bar_ms:
        failures.append(
            f"steady-state p99 {worst_steady_p99_ms:.0f} ms > "
            f"{p99_bar_ms:.0f} ms bar")
    if not result["breakers_closed"]:
        failures.append("a circuit breaker opened during the ramp")
    bad_gaps = [g for g in result["action_gaps_s"]
                if g < result["cooldown_s"] - 0.05]
    if bad_gaps:
        failures.append(
            f"scale actions {bad_gaps} s apart — faster than the "
            f"{result['cooldown_s']} s hysteresis cooldown (flap)")
    hung = sum(s["hung"] for s in result["segments"])
    if hung:
        failures.append(f"{hung} request(s) hung")
    if failures:
        bench_common.log("load_harness ramp FAIL: "
                         + "; ".join(failures))
        return 1
    bench_common.log(
        f"load_harness ramp PASS: replicas 1 -> "
        f"{result['replicas_max']} -> {result['replicas_end']}, "
        f"availability {availability:.4f}, steady p99 "
        f"{worst_steady_p99_ms:.0f} ms (bar {p99_bar_ms:.0f}), "
        f"actions {result['scale_actions']}")
    return 0


ACCOUNTING_CHILD_PREFIX = "ACCOUNTING_CHILD_RESULT "


def accounting_child() -> int:
    """The cost-attribution phase (own process — forced 2 host devices):
    three PCA models at 2 replicas each behind the REAL HTTP server,
    driven with a Zipf-weighted mix (hot takes most of the traffic, mid
    a trickle, cold goes quiet after a brief opening burst). What the
    parent judges from this child's output:

    * the ledger's summed per-model device-seconds RECONCILE against
      the independent devmon counter (both meters ride the same batch-
      completion seam, so drift beyond the documented tolerance means
      an attribution bug, not noise);
    * the ``/debug/costs`` cold-model report ranks the idle model
      colder than the hot one — resident bytes with no traffic is
      exactly what tiering wants surfaced;
    * scale-down releases accounted residency: after the soak the hot
      model drops to 1 replica, the reap moves the retired replica's
      weights bytes into the ``reserve`` component (the program is
      RETAINED for zero-cold-start revival, not freed)."""
    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        ServeEngine,
        start_serve_server,
    )

    soak_s = _env_float("SPARKML_LOAD_ACCT_SECONDS", 10.0)
    n_features = _env_int("SPARKML_LOAD_FEATURES", 16)
    k = _env_int("SPARKML_LOAD_K", 8)
    rng = np.random.default_rng(31)
    x = rng.normal(size=(2048, n_features))
    registry = ModelRegistry()
    models = ("acct_hot_pca", "acct_mid_pca", "acct_cold_pca")
    for name in models:
        registry.register(name, PCA().setK(k).fit(x))
    engine = ServeEngine(registry, max_batch_rows=256, max_wait_ms=2.0,
                         max_queue_depth=256)
    for name in models:
        engine.warmup(name)
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    # opening burst: every model takes a little traffic, so the cold
    # model has real rows on the meter — "cold" must mean went-idle
    # (age + ewma), not never-seen
    for name in models:
        burst = TenantLoad(base, name, x, tenant="acct",
                           priority="interactive", threads=2,
                           pace_rps_per_thread=0.0, rows_lo=16,
                           rows_hi=64, seed=7)
        burst.run(1.0)
    # Zipf-weighted soak: hot closed-loop, mid paced at a trickle, cold
    # silent — the 1/rank^s shape collapsed onto three tiers
    hot = TenantLoad(base, "acct_hot_pca", x, tenant="acct",
                     priority="interactive", threads=6,
                     pace_rps_per_thread=0.0, rows_lo=16, rows_hi=96,
                     seed=8)
    mid = TenantLoad(base, "acct_mid_pca", x, tenant="acct",
                     priority="interactive", threads=2,
                     pace_rps_per_thread=4.0, rows_lo=8, rows_hi=32,
                     seed=9)
    t0 = time.monotonic()
    threads = [
        threading.Thread(target=hot.run, args=(soak_s,), daemon=True),
        threading.Thread(target=mid.run, args=(soak_s,), daemon=True),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(soak_s + 60.0)
    wall = time.monotonic() - t0
    # let in-flight batches complete so both meters stop moving, then
    # read the rollup over the wire — the endpoint under test
    time.sleep(1.0)
    costs = _get_json(base, "/debug/costs")

    # scale-down leg: hot model to 1 replica, reap, re-read residency
    weights_before = {
        m: costs.get("models", {}).get(m, {}).get(
            "hbm_bytes", {}).get("weights", 0)
        for m in models
    }
    # scale_replicas reaps drained retirees itself; the loop only
    # covers replicas whose queues were still draining at that instant
    scale_report = engine.scale_replicas(1)
    retired = sum(d.get("retired", 0)
                  for d in scale_report.get("resized", {}).values())
    reap_deadline = time.monotonic() + 20.0
    while time.monotonic() < reap_deadline:
        engine.reap_retired()
        if engine.replica_scale() == 1:
            break
        time.sleep(0.25)
    costs_after = _get_json(base, "/debug/costs")
    hot_after = costs_after.get("models", {}).get(
        "acct_hot_pca", {}).get("hbm_bytes", {})

    server.shutdown()
    engine.shutdown()
    from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

    tsdb_mod.get_sampler().stop()
    time.sleep(1.0)

    hot_stats = hot.stats(wall)
    mid_stats = mid.stats(wall)
    # live replicas only — synthetic rows like "(sharded)" / "(aot)"
    # must not satisfy the >= 2-replica gate
    replica_counts = {
        m: sum(1 for key in
               costs.get("models", {}).get(m, {}).get("replicas", {})
               if not key.startswith("("))
        for m in models
    }
    result = {
        "devices": 2,
        "soak_seconds": wall,
        "hot_served_rows_per_sec": hot_stats["served_rows_per_sec"],
        "mid_served_rows_per_sec": mid_stats["served_rows_per_sec"],
        "hot_availability": hot_stats["availability"],
        "replica_counts": replica_counts,
        "reconcile": costs.get("reconcile", {}),
        "cold_report": costs.get("cold_report", []),
        "models": {
            m: {key: doc.get(key) for key in
                ("hbm_total_bytes", "device_seconds", "rows",
                 "ewma_rps", "last_hit_age_seconds")}
            for m, doc in costs.get("models", {}).items()
        },
        "weights_before": weights_before,
        "hot_weights_after": hot_after.get("weights", -1),
        "hot_reserve_after": hot_after.get("reserve", -1),
        "retired": retired,
    }
    sys.stdout.write(ACCOUNTING_CHILD_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0


def run_accounting_phase() -> int:
    """Parent leg of the cost-attribution phase: spawn the 2-device
    child, judge the gates, emit the sentinel record. Gates:

    * ledger-vs-devmon reconciliation verdict ``ok`` with worst drift
      within the documented tolerance
      (``SPARK_RAPIDS_ML_TPU_OBS_RECONCILE_TOL``, default 5%), at
      least one model over the attribution floor;
    * the cold-model report ranks the idle model colder than the hot
      one under the Zipf mix;
    * every model ran >= 2 replicas, and the scale-down reap moved the
      hot model's retired weights bytes into ``reserve`` (released
      from the live-weights component, retained for revival)."""
    import subprocess

    env = dict(os.environ)
    env["SPARKML_LOAD_PHASE"] = "accounting_child"
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["XLA_FLAGS"] = bench_common.force_device_count_flags(2)
    env.pop("SPARK_RAPIDS_ML_TPU_SERVE_REPLICAS", None)
    bench_common.log("load_harness accounting: child at 2 device(s), "
                     "Zipf hot/mid/cold mix across 3 models")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=420,
    )
    result = bench_common.prefixed_result(proc.stdout,
                                          ACCOUNTING_CHILD_PREFIX)
    if result is None:
        bench_common.log(
            f"load_harness accounting FAIL: child produced no result "
            f"(rc={proc.returncode}): {proc.stderr[-2000:]}")
        return 1
    reconcile = result["reconcile"]
    worst_drift = float(reconcile.get("worst_drift_ratio", 1.0))
    tolerance = float(reconcile.get("tolerance", 0.0))
    cold_rank = {doc["model"]: i
                 for i, doc in enumerate(result["cold_report"])}
    record = {
        "bench": "load_harness_accounting",
        "metric": "load_harness_accounting_worst_drift",
        "value": worst_drift,
        "unit": ("worst per-model relative drift between ledger and "
                 "devmon device-seconds at the batch-completion seam"),
        "higher_is_better": False,
        "platform": "cpu",
        "device_kind": "cpu",
        **{key: result[key] for key in
           ("devices", "soak_seconds", "replica_counts", "reconcile",
            "cold_report", "models", "weights_before",
            "hot_weights_after", "hot_reserve_after", "retired",
            "hot_served_rows_per_sec", "hot_availability")},
    }
    bench_common.emit_record(record, include_metrics=False)
    failures = []
    if reconcile.get("verdict") != "ok":
        failures.append(
            f"reconcile verdict {reconcile.get('verdict')!r} "
            f"(worst drift {worst_drift:.4f} vs tolerance "
            f"{tolerance:.4f})")
    if int(reconcile.get("models_checked", 0)) < 1:
        failures.append("no model crossed the reconcile attribution "
                        "floor — the soak metered nothing")
    if cold_rank.get("acct_cold_pca", 99) > cold_rank.get(
            "acct_hot_pca", -1):
        failures.append(
            f"cold report ranked hot before idle: {cold_rank}")
    thin = {m: n for m, n in result["replica_counts"].items() if n < 2}
    if thin:
        failures.append(f"models below 2 replicas during the soak: "
                        f"{thin}")
    hot_before = int(result["weights_before"].get("acct_hot_pca", 0))
    if not (0 <= result["hot_weights_after"] < hot_before):
        failures.append(
            f"scale-down did not release accounted weights bytes: "
            f"{hot_before} -> {result['hot_weights_after']}")
    if result["hot_reserve_after"] <= 0:
        failures.append(
            "reaped replica's bytes did not land in the reserve "
            "component — the retained program would be invisible")
    if failures:
        bench_common.log("load_harness accounting FAIL: "
                         + "; ".join(failures))
        return 1
    bench_common.log(
        f"load_harness accounting PASS: worst drift "
        f"{worst_drift:.4f} (tolerance {tolerance:.4f}, "
        f"{reconcile.get('models_checked')} model(s) checked), cold "
        f"report ranks {result['cold_report'][0]['model']} coldest, "
        f"hot weights {hot_before} -> {result['hot_weights_after']} "
        f"bytes with {result['hot_reserve_after']} in reserve after "
        f"scale-down")
    return 0


FITMON_CHILD_PREFIX = "FITMON_CHILD_RESULT "


def fitmon_child() -> int:
    """The fit-observability phase (own process — forced 2 host devices,
    fast sampler, fast watchdog, 1-sweep incident hysteresis). Four
    drills, all judged by the parent:

    * **visibility** — PCA and KMeans fits under the live stack (every
      ``@fit_instrumentation`` driver opens a FitRun), then
      ``GET /debug/fit`` over the wire must show per-step device time,
      rows/sec, and MFU for both algos. CPU has no real peak table, so
      the parent injects a synthetic one via
      ``SPARK_RAPIDS_ML_TPU_FITMON_PEAK_FLOPS`` — absent MFU on a
      configured-peaks backend is a broken attribution path, not an
      unknown device kind;
    * **reconcile** — fitmon's summed ``sparkml_fit_device_seconds_
      total`` against devmon's ``fit:*`` batch-seconds (the one
      measured duration feeds both meters, so drift is an attribution
      bug, not noise);
    * **straggler** — an injected per-host delay in a run's host-step
      table must trip the straggler flag for exactly that host;
    * **watchdog** — flipping the watchdog's expected platform to
      "tpu" (resolved: cpu) must open exactly ONE auto-resolving
      ``fit_backend_degraded`` incident; clearing the expectation must
      resolve it."""
    import jax

    from spark_rapids_ml_tpu.obs import fitmon, get_registry
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        ServeEngine,
        start_serve_server,
    )

    n_features = _env_int("SPARKML_LOAD_FEATURES", 32)
    k = _env_int("SPARKML_LOAD_K", 8)
    n_fits = _env_int("SPARKML_LOAD_FITMON_FITS", 3)

    registry = ModelRegistry()
    engine = ServeEngine(registry, max_batch_rows=128, max_wait_ms=2.0,
                         max_queue_depth=64)
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    def metric_sum(name: str, label: str = None,
                   prefix: str = None) -> float:
        snap = get_registry().snapshot().get(name, {"samples": []})
        total = 0.0
        for s in snap["samples"]:
            if prefix is not None and not str(
                    s["labels"].get(label, "")).startswith(prefix):
                continue
            total += s["value"]
        return total

    # -- visibility: monitored DISTRIBUTED fits under the live stack -------
    # (the parallel drivers are the instrumented surface — the forced
    # 2-device mesh is exactly what a real pod slice shard looks like)
    from spark_rapids_ml_tpu.parallel import (
        distributed_kmeans_fit,
        distributed_pca_fit,
    )
    from spark_rapids_ml_tpu.parallel.mesh import data_mesh

    mesh = data_mesh()
    rng = np.random.default_rng(11)
    x = rng.normal(size=(4096, n_features))
    for seed in range(n_fits):
        distributed_pca_fit(x, k, mesh)
        distributed_kmeans_fit(x, k, mesh, max_iter=10, seed=seed)
    fit_doc = _get_json(base, "/debug/fit")
    runs = fit_doc.get("recent", []) + fit_doc.get("active", [])

    def algo_evidence(algo: str) -> dict:
        mine = [r for r in runs if r.get("algo") == algo]
        return {
            "runs": len(mine),
            "steps": sum(r.get("steps", 0) for r in mine),
            "device_seconds": sum(
                r.get("device_seconds") or 0.0 for r in mine),
            "rows_per_sec_present": any(
                r.get("rows_per_sec") for r in mine),
            "mfu_present": any(
                r.get("mfu_mean") is not None for r in mine),
        }

    evidence = {
        "distributed_pca": algo_evidence("distributed_pca"),
        "distributed_kmeans": algo_evidence("distributed_kmeans"),
    }

    # -- reconcile: fitmon device-seconds vs the devmon meter --------------
    fitmon_s = metric_sum("sparkml_fit_device_seconds_total")
    devmon_s = metric_sum("sparkml_serve_device_batch_seconds_total",
                          label="model", prefix="fit:")
    drift = (abs(fitmon_s - devmon_s) / fitmon_s) if fitmon_s > 0 else 1.0

    # -- straggler: injected per-host delay --------------------------------
    monitor = fitmon.get_fit_monitor()
    run = monitor.start_run("straggler_drill")
    with run.step("drill", rows=256):
        pass
    run.note_host_step("host0", 0.10)
    run.note_host_step("host1", 0.11)
    run.note_host_step("host2", 0.45)  # the injected delay
    skew = run.skew()
    monitor.finish_run(run)

    # -- watchdog: platform-mismatch drill over the REAL pipeline ----------
    # (watchdog check → gauge → sampler sweep → ThresholdDetector →
    # incident engine), all on the live sampler thread at its fast
    # cadence. The expectation flip is the injected fault.
    wd = monitor.watchdog

    def fit_backend_incidents(doc: dict, state: str) -> list:
        return [i for i in doc.get(state, [])
                if i.get("detector") == fitmon.INCIDENT_NAME]

    def wait_for(predicate, timeout_s: float = 30.0) -> dict:
        deadline = time.monotonic() + timeout_s
        doc = {}
        while time.monotonic() < deadline:
            doc = _get_json(base, "/debug/incidents")
            if predicate(doc):
                return doc
            time.sleep(0.2)
        return doc

    wd.expected_platform = None
    wd.check()  # healthy baseline lands backend_ok=1 in the store
    time.sleep(1.0)
    wd.expected_platform = "tpu"  # resolved platform is cpu: degraded
    opened_doc = wait_for(
        lambda d: len(fit_backend_incidents(d, "open")) >= 1)
    open_incidents = fit_backend_incidents(opened_doc, "open")
    mismatch_verdict = wd.last_verdict() or {}
    wd.expected_platform = None  # fault cleared: must auto-resolve
    resolved_doc = wait_for(
        lambda d: not fit_backend_incidents(d, "open")
        and fit_backend_incidents(d, "recent"))
    resolved = fit_backend_incidents(resolved_doc, "recent")

    server.shutdown()
    engine.shutdown()
    from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

    tsdb_mod.get_sampler().stop()
    time.sleep(1.0)

    result = {
        "devices": jax.device_count(),
        "fits_per_algo": n_fits,
        "algos": evidence,
        "fit_doc_peaks": fit_doc.get("peaks", {}),
        "fitmon_device_seconds": fitmon_s,
        "devmon_fit_batch_seconds": devmon_s,
        "device_seconds_drift": drift,
        "skew": skew,
        "watchdog_mismatch_verdict": {
            key: mismatch_verdict.get(key)
            for key in ("ok", "reason", "platform", "expected_platform")
        },
        "incidents_opened": len(open_incidents),
        "incident_detectors": sorted(
            {i.get("detector") for i in open_incidents}),
        "incidents_resolved": len(resolved),
        "incident_states": sorted(
            {i.get("state") for i in resolved}),
    }
    sys.stdout.write(FITMON_CHILD_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0


def run_fitmon_phase() -> int:
    """Parent leg of the fit-observability phase: spawn the 2-device
    child with fast observability cadences, judge the gates, emit the
    sentinel record. Gates:

    * both fitted algos show steps with device time, rows/sec, AND MFU
      in ``/debug/fit`` (synthetic peak table injected — MFU absent
      would mean the TrackedJit→fitmon attribution path is severed);
    * fitmon's device-seconds reconcile with devmon's ``fit:*`` meter
      within ``SPARKML_LOAD_FITMON_DRIFT`` (default 5%);
    * the injected per-host delay flags exactly that host a straggler;
    * the platform-mismatch drill opens exactly one
      ``fit_backend_degraded`` incident and it auto-resolves once the
      expectation is cleared."""
    import subprocess

    drift_bar = _env_float("SPARKML_LOAD_FITMON_DRIFT", 0.05)
    env = dict(os.environ)
    env["SPARKML_LOAD_PHASE"] = "fitmon_child"
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["XLA_FLAGS"] = bench_common.force_device_count_flags(2)
    env["SPARK_RAPIDS_ML_TPU_OBS_SAMPLE_MS"] = "100"
    env["SPARK_RAPIDS_ML_TPU_FITMON_WATCHDOG_S"] = "0.2"
    env["SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_OPEN_AFTER"] = "1"
    env["SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_RESOLVE_AFTER"] = "2"
    env["SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_COOLDOWN_S"] = "0"
    env["SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_CAPTURE_S"] = "0"
    # CPU has no peak table; a synthetic one makes MFU a hard assertion
    env["SPARK_RAPIDS_ML_TPU_FITMON_PEAK_FLOPS"] = "1e12"
    env["SPARK_RAPIDS_ML_TPU_FITMON_PEAK_BW"] = "1e11"
    env.pop("SPARK_RAPIDS_ML_TPU_FITMON_EXPECT_PLATFORM", None)
    bench_common.log("load_harness fitmon: child at 2 device(s), "
                     "PCA+KMeans fits + straggler + watchdog drills")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=420,
    )
    result = bench_common.prefixed_result(proc.stdout,
                                          FITMON_CHILD_PREFIX)
    if result is None:
        bench_common.log(
            f"load_harness fitmon FAIL: child produced no result "
            f"(rc={proc.returncode}): {proc.stderr[-2000:]}")
        return 1
    drift = float(result["device_seconds_drift"])
    record = {
        "bench": "load_harness_fitmon",
        "metric": "load_harness_fitmon_device_drift",
        "value": drift,
        "unit": ("relative drift between fitmon step device-seconds "
                 "and the devmon fit:* batch meter"),
        "higher_is_better": False,
        "platform": "cpu",
        "device_kind": "cpu",
        "drift_bar": drift_bar,
        **{key: result[key] for key in
           ("devices", "fits_per_algo", "algos", "fitmon_device_seconds",
            "devmon_fit_batch_seconds", "skew",
            "watchdog_mismatch_verdict", "incidents_opened",
            "incident_detectors", "incidents_resolved",
            "incident_states")},
    }
    bench_common.emit_record(record, include_metrics=False)
    failures = []
    for algo, doc in result["algos"].items():
        if doc["runs"] < 1 or doc["steps"] < 1:
            failures.append(f"{algo}: no monitored runs/steps in "
                            f"/debug/fit ({doc})")
        if not doc["rows_per_sec_present"]:
            failures.append(f"{algo}: no per-step rows/sec")
        if doc["device_seconds"] <= 0:
            failures.append(f"{algo}: no per-step device time")
        if not doc["mfu_present"]:
            failures.append(f"{algo}: MFU absent despite injected peaks "
                            "— TrackedJit cost attribution severed")
    if drift > drift_bar:
        failures.append(
            f"fitmon/devmon device-seconds drift {drift:.4f} exceeds "
            f"{drift_bar:.4f} ({result['fitmon_device_seconds']:.4f}s "
            f"vs {result['devmon_fit_batch_seconds']:.4f}s)")
    if result["skew"].get("stragglers") != ["host2"]:
        failures.append(
            f"injected host2 delay not flagged: {result['skew']}")
    if result["incidents_opened"] != 1 or result[
            "incident_detectors"] != ["fit_backend_degraded"]:
        failures.append(
            f"platform-mismatch drill opened "
            f"{result['incidents_opened']} incident(s) "
            f"({result['incident_detectors']}), wanted exactly one "
            f"fit_backend_degraded")
    if result["incidents_resolved"] < 1 or result[
            "incident_states"] != ["resolved"]:
        failures.append(
            f"fit_backend_degraded did not auto-resolve after the "
            f"expectation was cleared: {result['incident_states']}")
    if failures:
        bench_common.log("load_harness fitmon FAIL: "
                         + "; ".join(failures))
        return 1
    bench_common.log(
        f"load_harness fitmon PASS: {result['fits_per_algo']} fit(s) "
        f"per algo visible with MFU, device-seconds drift "
        f"{drift:.4f} (bar {drift_bar:.4f}), straggler host2 flagged, "
        f"one fit_backend_degraded incident opened and auto-resolved")
    return 0


DENSITY_CHILD_PREFIX = "DENSITY_CHILD_RESULT "


class _ZipfLoad:
    """A closed-loop client fleet whose every request samples its MODEL
    from a Zipf(s) distribution over the registry — the thousand-model
    serving mix: one hot head, a long cold tail."""

    def __init__(self, base: str, names, x: np.ndarray, *,
                 threads: int, zipf_s: float, rows_lo: int,
                 rows_hi: int, seed: int = 0):
        self.base = base
        self.names = list(names)
        self.x = x
        self.threads = threads
        self.rows_lo, self.rows_hi = rows_lo, rows_hi
        self.seed = seed
        weights = np.array(
            [1.0 / (i + 1) ** zipf_s for i in range(len(self.names))])
        self.probs = weights / weights.sum()
        self.lock = threading.Lock()
        self.results = []  # (model_idx, status, latency_s, rows)

    def _client(self, idx: int, stop_at: float) -> None:
        rng = np.random.default_rng(self.seed * 1000 + idx)
        while time.monotonic() < stop_at:
            m = int(rng.choice(len(self.names), p=self.probs))
            n = int(rng.integers(self.rows_lo, self.rows_hi + 1))
            start = int(rng.integers(0, self.x.shape[0] - n))
            body = json.dumps({
                "model": self.names[m],
                "rows": self.x[start:start + n].tolist(),
                "tenant": "density",
                "priority": "interactive",
            }).encode()
            t0 = time.perf_counter()
            status, _retry, _shed = _post_predict(
                self.base, body, "density", "interactive")
            with self.lock:
                self.results.append(
                    (m, status, time.perf_counter() - t0, n))
            if status != 200:
                time.sleep(0.01)

    def run(self, seconds: float) -> None:
        stop_at = time.monotonic() + seconds
        workers = [
            threading.Thread(target=self._client, args=(i, stop_at),
                             daemon=True)
            for i in range(self.threads)
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join(seconds + 120.0)

    def model_stats(self, idx: int) -> dict:
        with self.lock:
            mine = [(s, lat) for m, s, lat, _n in self.results
                    if m == idx]
        lat_ok = sorted(lat for s, lat in mine if s == 200)

        def pct(q: float) -> float:
            if not lat_ok:
                return 0.0
            return lat_ok[min(int(q * len(lat_ok)), len(lat_ok) - 1)]

        return {
            "attempts": len(mine),
            "ok": len(lat_ok),
            "availability": (len(lat_ok) / len(mine)) if mine else 0.0,
            "p50_ms": pct(0.50) * 1000.0,
            "p99_ms": pct(0.99) * 1000.0,
        }

    def distinct_models_hit(self) -> int:
        with self.lock:
            return len({m for m, *_ in self.results})


def density_child() -> int:
    """One arm of the model-density phase (own process — forced 2 host
    devices). Registers ``SPARKML_LOAD_DENSITY_MODELS`` names of one
    fitted PCA behind the real HTTP server, drives a Zipf mix over ALL
    of them, and — when ``SPARKML_LOAD_DENSITY_TIERING=1`` — runs the
    ``TieringController`` against a ``budget_models``-model HBM budget
    while it soaks. The control arm (tiering off) is the same stack
    with nothing ever moved off the device: its residency only grows.
    Both arms count fresh XLA compiles during the soak — reactivation
    must be a disk replay through the executable cache, never a
    recompile storm."""
    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.obs import xprof
    from spark_rapids_ml_tpu.obs.aotcache import (
        configure_executable_cache,
    )
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        ServeEngine,
        TieringController,
        start_serve_server,
    )

    tiering_on = os.environ.get("SPARKML_LOAD_DENSITY_TIERING") == "1"
    n_models = _env_int("SPARKML_LOAD_DENSITY_MODELS", 200)
    budget_models = _env_int("SPARKML_LOAD_DENSITY_BUDGET_MODELS", 10)
    soak_s = _env_float("SPARKML_LOAD_DENSITY_SECONDS", 10.0)
    zipf_s = _env_float("SPARKML_LOAD_DENSITY_ZIPF_S", 1.1)
    threads = _env_int("SPARKML_LOAD_DENSITY_THREADS", 4)
    cache_dir = os.environ.get("SPARKML_LOAD_DENSITY_CACHE")
    if cache_dir:
        configure_executable_cache(cache_dir)

    n_features = _env_int("SPARKML_LOAD_FEATURES", 16)
    rng = np.random.default_rng(43)
    x = rng.normal(size=(1024, n_features))
    # ONE fitted model under many names: executables are weight-
    # independent and keyed on (label, signature), so the whole roster
    # shares one compiled ladder — warming name 0 warms the fleet
    model = PCA().setK(4).fit(x)
    registry = ModelRegistry()
    names = [f"density_{i:03d}" for i in range(n_models)]
    for name in names:
        registry.register(name, model)
    engine = ServeEngine(registry, max_batch_rows=64, max_wait_ms=1.0,
                         max_queue_depth=256, buckets=(64,))
    engine.placer.set_target(1)
    engine.warmup(names[0])
    # probe one TAIL model so the budget is sized from what a lazily
    # built replica actually charges (weights only — the warmed head
    # additionally carries the roster's shared executable bytes)
    engine.predict(names[1], x[:16])
    warm_base = sum(
        engine._ledger.memory_bytes(model=names[0]).values())
    per_model = sum(
        engine._ledger.memory_bytes(model=names[1]).values())
    budget = warm_base + budget_models * per_model

    ctrl = None
    if tiering_on:
        # the hot head is pinned: its warmed base (weights + attributed
        # executable bytes) stays resident, so the byte budget confines
        # the TAIL to ~budget_models lazily built residents
        ctrl = TieringController(
            engine, hbm_budget_bytes=budget, flap_floor_s=1.0,
            interval_s=0.25, per_model_autoscale=False, enabled=True,
            pins=(names[0],))
        engine.attach_tiering(ctrl)
        ctrl.start()
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    load = _ZipfLoad(base, names, x, threads=threads, zipf_s=zipf_s,
                     rows_lo=16, rows_hi=48, seed=5)
    xprof.reset_compile_log()
    t0 = time.monotonic()
    load.run(soak_s)
    wall = time.monotonic() - t0
    time.sleep(0.5)
    soak_compiles = sum(
        s["compiles"] for s in xprof.compile_stats().values())

    tiering_doc = _get_json(base, "/debug/tiering")
    if ctrl is not None:
        ctrl.stop()
        # settle: clients are gone, so tick until the budget holds —
        # models reactivated moments ago sit inside the flap floor and
        # need one more tick after it expires
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            ctrl.evaluate_once()
            if sum(engine._ledger.memory_bytes().values()) <= budget:
                break
            time.sleep(0.3)
    resident = engine._ledger.memory_bytes()
    resident_models = sum(1 for b in resident.values() if b > 0)
    resident_bytes = sum(resident.values())

    def tiering_count(event: str) -> float:
        from spark_rapids_ml_tpu.obs import get_registry
        snap = get_registry().snapshot().get(
            "sparkml_serve_tiering_total", {"samples": []})
        return sum(s["value"] for s in snap["samples"]
                   if s["labels"].get("event") == event)

    first_hits = [h["seconds"]
                  for h in (ctrl.lifecycle_history() if ctrl else [])
                  if h["event"] == "reactivate"]
    server.shutdown()
    engine.shutdown()
    from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

    tsdb_mod.get_sampler().stop()
    time.sleep(0.5)

    result = {
        "tiering": tiering_on,
        "devices": 2,
        "models": n_models,
        "budget_models": budget_models,
        "per_model_bytes": per_model,
        "warm_base_bytes": warm_base,
        "hbm_budget_bytes": budget,
        "soak_seconds": wall,
        "soak_compiles": soak_compiles,
        "distinct_models_hit": load.distinct_models_hit(),
        "resident_models_end": resident_models,
        "resident_bytes_end": resident_bytes,
        "hot": load.model_stats(0),
        "cold_hits": tiering_count("cold_hit"),
        "reactivates": tiering_count("reactivate"),
        "deactivates": tiering_count("deactivate"),
        "max_first_hit_s": max(first_hits, default=0.0),
        "tiering_state_counts": tiering_doc.get("state_counts", {}),
    }
    sys.stdout.write(DENSITY_CHILD_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0


def run_density_phase() -> int:
    """Parent leg of the model-density phase (ISSUE 19): spawn the
    2-device child twice — control (no tiering) and tiering under a
    ~``budget_models``-model HBM budget — over the SAME Zipf mix, judge
    the gates, emit the sentinel record. Gates:

    * the control arm's residency BLOWS THROUGH the budget (the
      problem is real on this mix: no eviction → every model ever hit
      stays resident);
    * the tiering arm ends byte-exact within the HBM budget, with the
      resident-model count at or under ``budget_models``;
    * cold first hits happened, every one completed its reactivation
      (``reactivate`` == ``cold_hit``), and the worst first-hit is
      bounded (``SPARKML_LOAD_DENSITY_FIRST_HIT_S``, default 2 s);
    * ZERO fresh XLA compiles during the tiering soak — every
      reactivation replayed through the executable cache;
    * the hot model's p99 under tiering stays within
      ``SPARKML_LOAD_DENSITY_P99_RATIO`` (default 2.5×) of the
      no-tiering control, with availability >= 0.99 in both arms —
      evicting the cold tail must not tax the hot head."""
    import subprocess
    import tempfile

    ratio_bar = _env_float("SPARKML_LOAD_DENSITY_P99_RATIO", 2.5)
    first_hit_bar = _env_float("SPARKML_LOAD_DENSITY_FIRST_HIT_S", 2.0)
    min_availability = _env_float("SPARKML_LOAD_MIN_AVAILABILITY", 0.99)
    arms = {}
    with tempfile.TemporaryDirectory(prefix="density_aot_") as tmp:
        for arm, flag in (("control", "0"), ("tiering", "1")):
            env = dict(os.environ)
            env["SPARKML_LOAD_PHASE"] = "density_child"
            env["SPARKML_LOAD_DENSITY_TIERING"] = flag
            env["SPARKML_LOAD_DENSITY_CACHE"] = os.path.join(tmp, arm)
            # 200 registered models must each keep their own ledger
            # label — the default 64-model fold would collapse the cold
            # tail into "(overflow)" and blind the eviction ranking
            env["SPARK_RAPIDS_ML_TPU_OBS_MODEL_MAX"] = "256"
            env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
            env["XLA_FLAGS"] = bench_common.force_device_count_flags(2)
            env.pop("SPARK_RAPIDS_ML_TPU_SERVE_REPLICAS", None)
            bench_common.log(
                f"load_harness density: {arm} child at 2 device(s)")
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=420,
            )
            result = bench_common.prefixed_result(
                proc.stdout, DENSITY_CHILD_PREFIX)
            if result is None:
                bench_common.log(
                    f"load_harness density FAIL: {arm} child produced "
                    f"no result (rc={proc.returncode}): "
                    f"{proc.stderr[-2000:]}")
                return 1
            arms[arm] = result
    control, tiering = arms["control"], arms["tiering"]
    control_p99 = float(control["hot"]["p99_ms"])
    tiering_p99 = float(tiering["hot"]["p99_ms"])
    p99_ratio = (tiering_p99 / control_p99) if control_p99 > 0 else 99.0
    record = {
        "bench": "load_harness_density",
        "metric": "load_harness_density_hot_p99_ratio",
        "value": p99_ratio,
        "unit": ("hot-model p99 under tiering vs the no-tiering "
                 "control on the same Zipf many-model mix"),
        "higher_is_better": False,
        "platform": "cpu",
        "device_kind": "cpu",
        "p99_ratio_bar": ratio_bar,
        "first_hit_bar_s": first_hit_bar,
        "control": control,
        "tiering": tiering,
    }
    bench_common.emit_record(record, include_metrics=False)
    failures = []
    if control["resident_bytes_end"] <= control["hbm_budget_bytes"]:
        failures.append(
            f"control residency {control['resident_bytes_end']} never "
            f"exceeded the budget {control['hbm_budget_bytes']} — the "
            "mix proves nothing")
    if tiering["resident_bytes_end"] > tiering["hbm_budget_bytes"]:
        failures.append(
            f"tiering residency {tiering['resident_bytes_end']} over "
            f"the {tiering['hbm_budget_bytes']}-byte budget")
    if tiering["resident_models_end"] > tiering["budget_models"] + 1:
        failures.append(
            f"{tiering['resident_models_end']} models resident, "
            f"budget {tiering['budget_models']} (+1 warmed head)")
    if tiering["cold_hits"] < 1:
        failures.append("no cold first hits — tiering never cycled")
    if tiering["reactivates"] != tiering["cold_hits"]:
        failures.append(
            f"{tiering['cold_hits']} cold hits but "
            f"{tiering['reactivates']} completed reactivations")
    if tiering["soak_compiles"] != 0:
        failures.append(
            f"{tiering['soak_compiles']} fresh XLA compile(s) during "
            "the tiering soak — reactivation is recompiling")
    if tiering["max_first_hit_s"] > first_hit_bar:
        failures.append(
            f"worst cold first-hit {tiering['max_first_hit_s']:.3f}s "
            f"> {first_hit_bar}s bar")
    if p99_ratio > ratio_bar:
        failures.append(
            f"hot p99 ratio {p99_ratio:.2f} (tiering "
            f"{tiering_p99:.0f}ms vs control {control_p99:.0f}ms) > "
            f"{ratio_bar}")
    for arm, doc in arms.items():
        if doc["hot"]["availability"] < min_availability:
            failures.append(
                f"{arm} hot availability "
                f"{doc['hot']['availability']:.4f} < "
                f"{min_availability}")
    if failures:
        bench_common.log("load_harness density FAIL: "
                         + "; ".join(failures))
        return 1
    bench_common.log(
        f"load_harness density PASS: {tiering['models']} models, "
        f"{tiering['resident_models_end']} resident (budget "
        f"{tiering['budget_models']}), {int(tiering['cold_hits'])} "
        f"cold hits all reactivated with 0 fresh compiles (worst "
        f"first-hit {tiering['max_first_hit_s'] * 1000:.0f} ms), hot "
        f"p99 ratio {p99_ratio:.2f} (bar {ratio_bar})")
    return 0


FLEET_CHILD_PREFIX = "FLEET_CHILD_READY "


def fleet_child() -> int:
    """One fleet peer: a self-driving serving process on a fixed port.

    The child stands up the REAL stack (fitted PCA → registry → engine →
    HTTP server with the live sampler, so ``/debug/fleet/export`` has a
    populated store to walk) and then generates its own modest predict
    traffic forever — the parent aggregator polls it over the wire and
    SIGKILLs it mid-drill, so this function never returns normally. The
    parent pins ``SPARK_RAPIDS_ML_TPU_FLEET_HOST`` so a respawned peer
    keeps its host identity and the ``fleet_host_down`` incident
    auto-resolves instead of leaking a ghost host."""
    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        ServeEngine,
        start_serve_server,
    )

    port = _env_int("SPARKML_LOAD_FLEET_PORT", 0)
    n_features = _env_int("SPARKML_LOAD_FEATURES", 16)
    k = _env_int("SPARKML_LOAD_K", 4)

    rng = np.random.default_rng(5)
    x = rng.normal(size=(1024, n_features))
    model = PCA().setK(k).fit(x)
    registry = ModelRegistry()
    registry.register("fleet_pca", model)
    engine = ServeEngine(registry, max_batch_rows=128, max_wait_ms=2.0,
                         max_queue_depth=256)
    server = start_serve_server(engine, port=port)
    sys.stdout.write(FLEET_CHILD_PREFIX + json.dumps(
        {"port": server.server_address[1]}) + "\n")
    sys.stdout.flush()
    while True:  # until SIGKILL — the parent owns this lifetime
        n = int(rng.integers(8, 64))
        start = int(rng.integers(0, x.shape[0] - n))
        try:
            engine.predict("fleet_pca", x[start:start + n])
        except Exception:  # noqa: BLE001 - shed/overload is fine here
            pass
        time.sleep(0.02)


def run_fleet_phase() -> int:
    """The fleet-federation phase: 2 serving subprocesses through ONE
    in-process aggregator. The parent IS the fleet brain — it runs the
    sampler + incident engine + forecaster + ``FleetAggregator`` that a
    real deployment would run on its coordinator host. Gates:

    * both peers polled ok and the MERGED store carries the same series
      under both ``host=`` labels (federation actually federates);
    * SIGKILLing peer B opens exactly ONE ``fleet_host_down`` incident
      (for hostB only — hostA must stay clean) through the standard
      sampler→detector→incident pipeline, and respawning the peer on
      the same host identity + port auto-resolves it;
    * the Holt forecaster's backtest relative error on the fleet
      request-rate signal is under ``SPARKML_LOAD_FLEET_FORECAST_ERR``
      (default 0.5) after the soak — the predictive plane's evidence
      that its projections track reality."""
    import socket
    import subprocess

    forecast_err_bar = _env_float("SPARKML_LOAD_FLEET_FORECAST_ERR", 0.5)
    soak_s = _env_float("SPARKML_LOAD_FLEET_SOAK_SECONDS", 12.0)

    # fast cadences BEFORE the obs singletons are constructed (children
    # inherit these via the spawn env, so both sides sweep at 100 ms
    # and incidents open after 1 sweep / resolve after 2)
    os.environ["SPARK_RAPIDS_ML_TPU_OBS_SAMPLE_MS"] = "100"
    os.environ["SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_OPEN_AFTER"] = "1"
    os.environ["SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_RESOLVE_AFTER"] = "2"
    os.environ["SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_COOLDOWN_S"] = "0"
    os.environ["SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_CAPTURE_S"] = "0"

    from spark_rapids_ml_tpu.obs import (
        federation,
        forecast,
        incidents as incidents_mod,
        tsdb as tsdb_mod,
    )

    def free_port() -> int:
        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()
        return port

    ports = {"hostA": free_port(), "hostB": free_port()}
    bases = {h: f"http://127.0.0.1:{p}" for h, p in ports.items()}
    procs: dict = {}

    def spawn(host: str) -> None:
        env = dict(os.environ)
        env["SPARKML_LOAD_PHASE"] = "fleet_child"
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
        env["SPARKML_LOAD_FLEET_PORT"] = str(ports[host])
        env["SPARK_RAPIDS_ML_TPU_FLEET_HOST"] = host
        procs[host] = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def wait_ready(host: str, timeout_s: float = 90.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if _get_json(bases[host], "/healthz"):  # {} while booting
                return True
            time.sleep(0.2)
        return False

    def wait_for(predicate, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.2)
        return False

    def fleet_incidents(state: str) -> list:
        digest = inc_engine.digest()
        return [i for i in digest.get(state, [])
                if i.get("detector") == federation.INCIDENT_NAME]

    bench_common.log("load_harness fleet: spawning 2 serving peers "
                     f"(hostA:{ports['hostA']}, hostB:{ports['hostB']})")
    for host in sorted(ports):
        spawn(host)
    agg = None
    failures = []
    try:
        for host in sorted(ports):
            if not wait_ready(host):
                bench_common.log(
                    f"load_harness fleet FAIL: {host} never became "
                    f"ready on {bases[host]}")
                return 1

        sampler = tsdb_mod.start_sampling()
        inc_engine = incidents_mod.get_incident_engine()
        inc_engine.install(sampler)
        forecaster = forecast.get_forecaster()
        forecaster.install(sampler)
        agg = federation.FleetAggregator(
            [(host, bases[host]) for host in sorted(ports)],
            poll_interval_s=0.25, stale_after_s=1.0,
            fetch_timeout_s=1.0, forecaster=forecaster)
        federation.set_aggregator(agg)
        agg.start()

        # -- soak: merged series must carry BOTH host labels ---------------
        def merged_hosts() -> set:
            found = set()
            for row in agg.store().range_query(
                    "sparkml_serve_requests_total", window=120.0):
                host = row["labels"].get("host")
                if host:
                    found.add(host)
            return found

        time.sleep(soak_s)
        both_merged = wait_for(
            lambda: merged_hosts() >= set(ports), timeout_s=30.0)
        hosts_seen = sorted(merged_hosts())
        rollup = agg.rollup()
        if not both_merged:
            failures.append(
                f"merged store carries host labels {hosts_seen}, "
                f"wanted both of {sorted(ports)}")
        if rollup["hosts_up"] != len(ports):
            failures.append(
                f"{rollup['hosts_up']}/{len(ports)} hosts up after "
                f"soak: {rollup['hosts']}")

        # -- kill drill: SIGKILL hostB → exactly one fleet_host_down -------
        procs["hostB"].kill()
        procs["hostB"].wait()
        opened = wait_for(lambda: len(fleet_incidents("open")) >= 1)
        open_incs = fleet_incidents("open")
        open_hosts = sorted({(i.get("labels") or {}).get("host")
                             for i in open_incs})
        if not opened or len(open_incs) != 1 or open_hosts != ["hostB"]:
            failures.append(
                f"kill drill wanted exactly one open "
                f"{federation.INCIDENT_NAME} for hostB, got "
                f"{len(open_incs)} for hosts {open_hosts}")

        # -- respawn on the SAME identity + port → must auto-resolve -------
        spawn("hostB")
        if not wait_ready("hostB"):
            failures.append("respawned hostB never became ready")
        resolved = wait_for(
            lambda: not fleet_incidents("open")
            and any(i.get("state") == "resolved"
                    for i in fleet_incidents("recent")))
        total_fleet_incidents = (
            len(fleet_incidents("open")) + len(fleet_incidents("recent")))
        if not resolved:
            failures.append(
                f"{federation.INCIDENT_NAME} did not auto-resolve after "
                f"respawn: open={fleet_incidents('open')} "
                f"recent={fleet_incidents('recent')}")
        if total_fleet_incidents != 1:
            failures.append(
                f"kill drill produced {total_fleet_incidents} "
                f"{federation.INCIDENT_NAME} incident(s), wanted "
                f"exactly one (flapping or a ghost host)")

        # -- forecaster backtest over the merged fleet rate ----------------
        fc = forecaster.snapshot()
        rps = fc["signals"].get("rps", {})
        backtest = rps.get("backtest", {})
        rel_err = backtest.get("rel_err_mean")
        if rps.get("updates", 0) < 10 or rel_err is None:
            failures.append(
                f"forecaster starved: {rps.get('updates', 0)} rps "
                f"updates, rel_err={rel_err}")
        elif rel_err > forecast_err_bar:
            failures.append(
                f"forecast backtest rel err {rel_err:.4f} exceeds "
                f"bar {forecast_err_bar:.4f}")
        rollup = agg.rollup()
    finally:
        if agg is not None:
            agg.stop()
            federation.set_aggregator(None)
        for proc in procs.values():
            try:
                proc.kill()
            except Exception:  # noqa: BLE001 - already dead is fine
                pass

    record = {
        "bench": "load_harness_fleet",
        "metric": "load_harness_fleet_forecast_rel_err",
        "value": rel_err if rel_err is not None else 1.0,
        "unit": ("Holt backtest |err| / |value| on the merged fleet "
                 "request-rate signal over the soak"),
        "higher_is_better": False,
        "platform": "cpu",
        "device_kind": "cpu",
        "peers": len(ports),
        "soak_seconds": soak_s,
        "forecast_err_bar": forecast_err_bar,
        "merged_host_labels": hosts_seen,
        "hosts_up_after_soak": rollup["hosts_up"],
        "merged_points": {
            row["host"]: row["merged_points"]
            for row in rollup["hosts"]},
        "fleet_incidents_total": total_fleet_incidents,
        "incident_auto_resolved": resolved,
        "forecast": fc["signals"],
    }
    bench_common.emit_record(record, include_metrics=False)
    if failures:
        bench_common.log("load_harness fleet FAIL: "
                         + "; ".join(failures))
        return 1
    bench_common.log(
        f"load_harness fleet PASS: both peers merged under host labels "
        f"{hosts_seen}, kill drill opened exactly one auto-resolving "
        f"{federation.INCIDENT_NAME}, forecast backtest rel err "
        f"{rel_err:.4f} (bar {forecast_err_bar:.4f})")
    return 0


def main() -> int:
    if os.environ.get("SPARKML_LOAD_PHASE") == "device_capacity_child":
        return device_capacity_child()
    if os.environ.get("SPARKML_LOAD_PHASE") == "ramp_child":
        return ramp_child()
    if os.environ.get("SPARKML_LOAD_PHASE") == "ramp":
        return run_ramp_phase()
    if os.environ.get("SPARKML_LOAD_PHASE") == "accounting_child":
        return accounting_child()
    if os.environ.get("SPARKML_LOAD_PHASE") == "accounting":
        return run_accounting_phase()
    if os.environ.get("SPARKML_LOAD_PHASE") == "fitmon_child":
        return fitmon_child()
    if os.environ.get("SPARKML_LOAD_PHASE") == "fitmon":
        return run_fitmon_phase()
    if os.environ.get("SPARKML_LOAD_PHASE") == "density_child":
        return density_child()
    if os.environ.get("SPARKML_LOAD_PHASE") == "density":
        return run_density_phase()
    if os.environ.get("SPARKML_LOAD_PHASE") == "fleet_child":
        return fleet_child()
    if os.environ.get("SPARKML_LOAD_PHASE") == "fleet":
        return run_fleet_phase()
    soak_s = _env_float("SPARKML_LOAD_SOAK_SECONDS", 60.0)
    calibrate_s = _env_float("SPARKML_LOAD_CALIBRATE_SECONDS", 8.0)
    n_features = _env_int("SPARKML_LOAD_FEATURES", 16)
    k = _env_int("SPARKML_LOAD_K", 8)
    greedy_threads = _env_int("SPARKML_LOAD_GREEDY_THREADS", 24)
    compliant_threads = _env_int("SPARKML_LOAD_COMPLIANT_THREADS", 4)
    min_availability = _env_float("SPARKML_LOAD_MIN_AVAILABILITY", 0.99)
    throughput_fraction = _env_float(
        "SPARKML_LOAD_THROUGHPUT_FRACTION", 0.9)
    # compliant p99 bar: explicit env wins; 0 (the default) derives it
    # from calibration — max(the serve latency SLO threshold, 2x the
    # single-tenant p99 at capacity). On a fast chip the SLO threshold
    # governs; on a slow shared-GIL CPU container the relative bar still
    # proves the fairness property (overload must not make the
    # protected tenant materially slower than the unloaded system).
    p99_bar_env = _env_float("SPARKML_LOAD_P99_MS", 0.0)

    import jax

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        ServeEngine,
        ShedController,
        start_serve_server,
    )

    device = jax.devices()[0]
    rng = np.random.default_rng(17)
    x = rng.normal(size=(2048, n_features))
    model = PCA().setK(k).fit(x)
    registry = ModelRegistry()
    registry.register("load_pca", model)

    # -- phase 1: calibrate single-tenant capacity -------------------------
    bench_common.log("load_harness calibrate")
    cal_engine = ServeEngine(registry, max_batch_rows=256, max_wait_ms=2.0,
                             max_queue_depth=64)
    cal_engine.warmup("load_pca")
    cal_server = start_serve_server(cal_engine)
    cal_base = f"http://127.0.0.1:{cal_server.server_address[1]}"
    # Calibrate at the SOAK's total concurrency with a comparable size
    # mix — capacity measured at a different operating point is not a
    # capacity the soak's throughput can honestly be compared against.
    cal = TenantLoad(cal_base, "load_pca", x, tenant="calibrate",
                     priority="interactive",
                     threads=compliant_threads + greedy_threads,
                     pace_rps_per_thread=0.0, rows_lo=8, rows_hi=48,
                     seed=1)
    t0 = time.monotonic()
    cal.run(calibrate_s)
    cal_wall = time.monotonic() - t0
    cal_stats = cal.stats(cal_wall)
    cal_server.shutdown()
    cal_engine.shutdown()
    capacity_rows = max(cal_stats["served_rows_per_sec"], 1.0)
    p99_bar_ms = p99_bar_env if p99_bar_env > 0 else max(
        _env_float("SPARK_RAPIDS_ML_TPU_SLO_LATENCY_THRESHOLD_MS", 250.0),
        2000.0 * cal_stats["p99"])
    bench_common.log(
        f"load_harness capacity {capacity_rows:,.0f} rows/s "
        f"({cal_stats['offered_rps']:.0f} req/s), single-tenant p99 "
        f"{cal_stats['p99'] * 1000:.0f} ms -> compliant bar "
        f"{p99_bar_ms:.0f} ms")

    # -- phase 2: the 2x overload soak -------------------------------------
    # Work-conserving quota split from measured capacity: greedy is
    # PROVISIONED 45% and compliant 30% (offered ~25%) — the greedy
    # flood beyond its 45% is the over-quota excess the controller
    # sheds, so total served stays near capacity while the excess
    # absorbs every rejection.
    greedy_quota = max(capacity_rows * 0.45, 50.0)
    compliant_quota = max(capacity_rows * 0.30, 200.0)
    # The shed controller targets a FIXED queue wait (default 100 ms,
    # env SPARKML_LOAD_SHED_WAIT_MS) rather than a fraction of the p99
    # bar: the controller's job is to keep queueing bounded; the bar
    # only judges the outcome.
    shed = ShedController(
        queue_wait_target_s=_env_float(
            "SPARKML_LOAD_SHED_WAIT_MS", 100.0) / 1000.0,
        hold_seconds=1.0,
    )
    engine = ServeEngine(
        registry, max_batch_rows=256, max_wait_ms=2.0,
        max_queue_depth=64,
        tenant_quotas={
            "greedy": (greedy_quota, greedy_quota),
            "compliant": (compliant_quota, 2.0 * compliant_quota),
        },
        shed=shed,
    )
    engine.warmup("load_pca")
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"

    # compliant pacing: ~25% of capacity in rows/s → req/s at the mean
    # request size, split across its threads
    mean_rows = (4 + 16) / 2.0
    compliant_rps = max(capacity_rows * 0.25 / mean_rows, 1.0)
    compliant = TenantLoad(
        base, "load_pca", x, tenant="compliant", priority="interactive",
        threads=compliant_threads,
        pace_rps_per_thread=compliant_rps / max(compliant_threads, 1),
        rows_lo=4, rows_hi=16, seed=2)
    # Greedy request size auto-scales from calibration so the flood is
    # a genuine 2x+ overload REGARDLESS of how fast this machine is
    # today: a closed loop can only offer threads/latency requests per
    # second, so the rows-per-request must carry the excess. Factor 3.0
    # (was 2.2): the closed loop's request latency under overload runs
    # well past the CALIBRATION p50 this formula divides by, so the
    # realized offer undershoots the target — and after the PR 12 wire
    # wins lifted single-tenant capacity ~5x, 2.2 stopped clearing the
    # >= 1.5x offered gate on fast containers at all.
    closed_loop_rps = greedy_threads / max(cal_stats["p50"], 0.02)
    greedy_rows = int(min(max(
        3.0 * capacity_rows / max(closed_loop_rps, 1.0), 32), 176))
    greedy = TenantLoad(
        base, "load_pca", x, tenant="greedy", priority="batch",
        threads=greedy_threads, pace_rps_per_thread=0.0,
        rows_lo=max(greedy_rows // 2, 16),
        rows_hi=min(greedy_rows + greedy_rows // 2, 240),
        reject_pause_s=0.02, deadline_ms=3000.0, seed=3)

    bench_common.log(
        f"load_harness soak {soak_s:.0f}s (greedy quota "
        f"{greedy_quota:,.0f} rows/s, {greedy_threads} closed-loop "
        f"threads)")
    readyz_shedding_seen = False
    shed_level_max = 0

    def _watch_readyz(stop_at: float) -> None:
        nonlocal readyz_shedding_seen, shed_level_max
        while time.monotonic() < stop_at:
            doc = _get_json(base, "/readyz")
            if doc.get("status") == "shedding":
                readyz_shedding_seen = True
                shed_level_max = max(shed_level_max,
                                     int(doc.get("shed_level", 1)))
            time.sleep(0.5)

    stop_at = time.monotonic() + soak_s
    watcher = threading.Thread(target=_watch_readyz, args=(stop_at,),
                               daemon=True)
    watcher.start()
    t0 = time.monotonic()
    threads = [
        threading.Thread(target=compliant.run, args=(soak_s,),
                         daemon=True),
        threading.Thread(target=greedy.run, args=(soak_s,), daemon=True),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(soak_s + 120.0)
    wall = time.monotonic() - t0
    watcher.join(5.0)

    compliant_stats = compliant.stats(wall)
    greedy_stats = greedy.stats(wall)
    breakers = engine.breaker_snapshot()
    overload = engine.overload_state()
    slo_doc = _get_json(base, "/debug/slo")
    server.shutdown()
    engine.shutdown()
    # Let the background sampler/worker threads leave their jax calls
    # before interpreter teardown — a daemon thread mid-dispatch at exit
    # aborts the process AFTER the verdict (the chaos-drill lesson).
    from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

    tsdb_mod.get_sampler().stop()
    time.sleep(1.0)

    # -- phase 3: device scaling (ISSUE 13) --------------------------------
    device_scaling: dict = {}
    scaling_min = _env_float("SPARKML_LOAD_DEVICE_SCALING_MIN", 1.6)
    if _env_float("SPARKML_LOAD_DEVICE_SCALING", 1.0) > 0:
        device_scaling = run_device_scaling_phase()
        if "error" not in device_scaling:
            bench_common.log(
                f"load_harness device scaling: "
                f"{device_scaling['one_device']['capacity_rows_per_sec']:,.0f}"
                f" rows/s at 1 device -> "
                f"{device_scaling['two_devices']['capacity_rows_per_sec']:,.0f}"
                f" at 2 ({device_scaling['capacity_ratio']:.2f}x), "
                f"2-device p99 "
                f"{device_scaling['two_devices']['p99_ms']:.0f} ms vs "
                f"{device_scaling['p99_bar_ms']:.0f} ms bar")

    total_served = (compliant_stats["served_rows_per_sec"]
                    + greedy_stats["served_rows_per_sec"])
    total_offered = (compliant_stats["offered_rows_per_sec"]
                     + greedy_stats["offered_rows_per_sec"])
    breakers_closed = all(b["state"] == "closed"
                          for b in breakers.values()) if breakers else True
    record = {
        "bench": "load_harness",
        # the headline the sentinel judges: the fairness contract —
        # explicit direction, immune to unit-text heuristics
        "metric": "load_harness_compliant_availability",
        "value": compliant_stats["availability"],
        "unit": "fraction of compliant-tenant requests answered 200",
        "higher_is_better": True,
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "soak_seconds": wall,
        "capacity_rows_per_sec": capacity_rows,
        "offered_rows_per_sec": total_offered,
        "offered_over_capacity": (total_offered / capacity_rows
                                  if capacity_rows else 0.0),
        "served_rows_per_sec": total_served,
        "throughput_fraction": (total_served / capacity_rows
                                if capacity_rows else 0.0),
        "compliant": compliant_stats,
        "greedy": greedy_stats,
        "p50": compliant_stats["p50"],
        "p99": compliant_stats["p99"],
        "percentiles": {"p50": compliant_stats["p50"],
                        "p99": compliant_stats["p99"]},
        "calibrate_p50": cal_stats["p50"],
        "calibrate_p99": cal_stats["p99"],
        "p99_bar_ms": p99_bar_ms,
        "readyz_shedding_seen": readyz_shedding_seen,
        "shed_level_max": shed_level_max,
        "breakers_closed": breakers_closed,
        "device_scaling": device_scaling,
        "shed_snapshot": overload.get("shed", {}),
        "tenants": overload.get("tenants", {}),
        "slo_alerts_firing": len(slo_doc.get("alerts", [])),
    }
    bench_common.emit_record(record)

    failures = []
    if compliant_stats["availability"] < min_availability:
        failures.append(
            f"compliant availability {compliant_stats['availability']:.4f}"
            f" < {min_availability}")
    if compliant_stats["p99"] * 1000.0 > p99_bar_ms:
        failures.append(
            f"compliant p99 {compliant_stats['p99'] * 1000:.1f} ms > "
            f"{p99_bar_ms} ms bar")
    if record["throughput_fraction"] < throughput_fraction:
        failures.append(
            f"throughput {record['throughput_fraction']:.2f} of capacity "
            f"< {throughput_fraction}")
    min_offered = _env_float("SPARKML_LOAD_MIN_OFFERED", 1.5)
    if record["offered_over_capacity"] < min_offered:
        failures.append(
            f"offered load only {record['offered_over_capacity']:.2f}x "
            f"capacity < {min_offered}x — not an overload soak")
    if not breakers_closed:
        failures.append(
            "a circuit breaker opened under pure overload — overload "
            "must never read as backend failure")
    if compliant_stats["shed"] > 0:
        failures.append(
            f"{compliant_stats['shed']} compliant (in-quota interactive) "
            "requests were shed — the controller must never shed them")
    if compliant_stats["hung"] or greedy_stats["hung"]:
        failures.append(
            f"{compliant_stats['hung'] + greedy_stats['hung']} "
            "request(s) hung")
    if device_scaling:
        if "error" in device_scaling:
            failures.append(
                f"device-scaling phase broke: {device_scaling['error']}")
        else:
            if device_scaling["capacity_ratio"] < scaling_min:
                failures.append(
                    f"2-device capacity only "
                    f"{device_scaling['capacity_ratio']:.2f}x the "
                    f"1-device calibration < {scaling_min}x")
            if not device_scaling["p99_under_bar"]:
                failures.append(
                    f"2-device p99 "
                    f"{device_scaling['two_devices']['p99_ms']:.0f} ms "
                    f"over the single-device bar "
                    f"{device_scaling['p99_bar_ms']:.0f} ms")
            if device_scaling["two_devices"]["hung"] or \
                    device_scaling["one_device"]["hung"]:
                failures.append("device-scaling request(s) hung")
    if failures:
        bench_common.log("load_harness FAIL: " + "; ".join(failures))
        return 1
    bench_common.log(
        f"load_harness PASS: compliant availability "
        f"{compliant_stats['availability']:.4f} at "
        f"{record['offered_over_capacity']:.1f}x offered load, "
        f"throughput {record['throughput_fraction']:.2f}x capacity, "
        f"greedy availability {greedy_stats['availability']:.3f} "
        f"({greedy_stats['shed']} shed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
