"""Round-4 wave-3: retry the UMAP half of the 200k scale demonstration.

Wave 1's scale step recorded DBSCAN at 200k×64 (10.82s, tiled) but UMAP
died at `block_until_ready` with UNAVAILABLE ("TPU device error") —
either collateral from a concurrent claim or a real fault in the blocked
UMAP path at this scale. This retry distinguishes the two: a clean pass
lands the missing record; a repeat failure at the same spot is a bug.

Single process, one claim; exit 2 when no chip (wrapper retries).
"""

from __future__ import annotations

import datetime
import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "records", "r04")
sys.path.insert(0, REPO)


def stamp() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def log(msg: str) -> None:
    os.makedirs(OUT, exist_ok=True)
    with open(os.path.join(OUT, "status.log"), "a") as f:
        f.write(f"{msg}: {stamp()}\n")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "tpu")
    log("wave3 probe start")
    try:
        import jax

        device = jax.devices()[0]
    except Exception as exc:  # noqa: BLE001
        log(f"wave3 probe FAILED ({type(exc).__name__})")
        return 2
    if device.platform == "cpu":
        log("wave3 probe FAILED (cpu backend)")
        return 2
    log("wave3 probe ok")

    import numpy as np

    from spark_rapids_ml_tpu.models.umap import UMAP

    rows, cols, block, epochs = 200_000, 64, 4096, 50
    rng = np.random.default_rng(0)
    n_blobs = 16
    centers = rng.normal(scale=12.0, size=(n_blobs, cols))
    assign = rng.integers(0, n_blobs, size=rows)
    x = centers[assign] + rng.normal(size=(rows, cols))

    try:
        t0 = time.perf_counter()
        um = (UMAP().setNNeighbors(15).setNEpochs(epochs)
              .setBlockRows(block).fit(x))
        seconds = time.perf_counter() - t0
        emb = np.asarray(um.embedding_)
        assert np.isfinite(emb).all()
        cent = np.stack([emb[assign == b].mean(axis=0)
                         for b in range(n_blobs)])
        intra = float(np.mean([
            np.linalg.norm(emb[assign == b] - cent[b], axis=1).mean()
            for b in range(n_blobs)]))
        inter = float(np.linalg.norm(
            cent[:, None, :] - cent[None, :, :], axis=-1
        )[np.triu_indices(n_blobs, 1)].mean())
        rec = {
            "metric": f"UMAP.fit seconds ({rows}x{cols}, tiled "
                      f"block={block}, epochs={epochs})",
            "value": round(seconds, 2),
            "unit": "seconds",
            "rows": rows,
            "platform": device.platform,
            "device_kind": str(getattr(device, "device_kind", "?")),
            "rows_per_sec": round(rows / seconds, 1),
            "separation_ratio": round(inter / max(intra, 1e-9), 2),
            "dense_equivalent_bytes": rows * rows * 4,
            "fit_timings": um.fit_timings_,
            "recorded_utc": stamp(),
        }
        assert inter > 1.15 * intra
        with open(os.path.join(OUT, "scale_umap.json"), "w") as f:
            f.write(json.dumps(rec) + "\n")
        log("wave3 umap ok")
    except Exception as exc:  # noqa: BLE001
        with open(os.path.join(OUT, "scale_umap.err"), "w") as f:
            f.write(f"{type(exc).__name__}: {exc}\n")
            f.write(traceback.format_exc())
        log(f"wave3 umap FAILED ({type(exc).__name__})")
        # a repeat UNAVAILABLE at the same spot is evidence of a real
        # fault — still exit 0 so the wrapper doesn't burn retries on a
        # deterministic failure (the .err file carries the verdict)
    # Clean config-3 re-run: the wave-1 config3 record (03:24-03:45Z)
    # overlapped a concurrent chip claim (an ALS verification drive), so
    # its arms ran contended. This re-measure is the quiet-chip number.
    log("wave3 config3 start")
    import contextlib
    import io

    import bench

    os.environ["BENCH_SKIP_PROBE"] = "1"
    os.environ["BENCH_ROWS"] = "1048576"
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    except Exception as exc:  # noqa: BLE001
        with open(os.path.join(OUT, "bench_config3_clean.err"), "w") as f:
            f.write(f"{type(exc).__name__}: {exc}\n")
            f.write(traceback.format_exc())
        log("wave3 config3 FAILED")
    else:
        lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
        try:
            rec = json.loads(lines[-1])
            rec["recorded_utc"] = stamp()
            rec["note"] = "quiet-chip re-measure of wave-1 config3"
            lines[-1] = json.dumps(rec)
        except Exception:  # noqa: BLE001
            pass
        with open(os.path.join(OUT, "bench_config3_clean.json"), "w") as f:
            f.write("\n".join(lines) + "\n")
        log("wave3 config3 ok")

    with open(os.path.join(OUT, "wave3_done"), "w") as f:
        f.write(stamp() + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
