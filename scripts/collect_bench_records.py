"""Compose BENCH_MEASURED_r04.json from the patient bench loop's outputs.

Reads /tmp/bench_r04/*.json (written by scripts/archive/bench_r04.sh in the first
healthy tunnel window), extracts every JSON record line, and writes the
committed measurement file BASELINE.md cites — with UTC stamp and the
repo commit so every number greps to a reproducible artifact (VERDICT r3
task #1). Run from the repo root AFTER the loop's done marker appears;
then update BASELINE.md rows and commit both.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

OUT_DIR = sys.argv[1] if len(sys.argv) > 1 else "/tmp/bench_r04"


def _records(path):
    rows = []
    if not os.path.exists(path):
        return rows
    for line in open(path):
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return rows


def main() -> None:
    proc = subprocess.run(
        ["git", "rev-parse", "--short", "HEAD"],
        capture_output=True, text=True,
    )
    commit = proc.stdout.strip()
    if proc.returncode != 0 or not commit:
        print("run from the repo root (git rev-parse failed)",
              file=sys.stderr)
        sys.exit(1)
    doc = {
        "note": (
            "Live-chip measurements captured by the round-4 patient bench "
            "loop (scripts/archive/bench_r04.sh: probe -> full evidence batch in "
            "one healthy window; logs in the loop's status.log). Committed "
            "so every BASELINE.md number greps to a recorded artifact."
        ),
        "commit": commit,
        "collected_utc": time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime()),
    }
    for name, key in (
        ("bench_config4.json", "headline"),
        ("bench_config3.json", "config3_1M_rows"),
        ("bench_config2.json", "config2_mnist_shape"),
    ):
        rows = _records(os.path.join(OUT_DIR, name))
        if rows:
            doc[key] = rows[-1]
    models = _records(os.path.join(OUT_DIR, "bench_models.json"))
    if models:
        doc["config5_models"] = models
    scale = _records(os.path.join(OUT_DIR, "bench_scale.json"))
    if scale:
        doc["scale_200k"] = scale
    sweep = _records(os.path.join(OUT_DIR, "bench_gram_sweep.json"))
    if sweep:
        doc["gram_sweep"] = sweep
    has_bench_records = len(doc) > 3  # beyond note/commit/collected_utc
    smoke = os.path.join(OUT_DIR, "pjrt_smoke.log")
    if os.path.exists(smoke):
        tail = open(smoke).read().strip().splitlines()
        doc["native_pjrt_client"] = {
            "verified": tail[-1] if tail else "",
            "measured_utc": doc["collected_utc"],
        }
    if not has_bench_records:
        print("no bench records found in", OUT_DIR, file=sys.stderr)
        sys.exit(1)
    with open("BENCH_MEASURED_r04.json", "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps({k: bool(v) for k, v in doc.items()
                      if k not in ("note", "commit", "collected_utc")}))
    print("wrote BENCH_MEASURED_r04.json @", commit)


if __name__ == "__main__":
    main()
