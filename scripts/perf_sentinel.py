#!/usr/bin/env python
"""Offline perf-regression sentinel: verdicts over bench records.

Compares one bench record (any ``emit_record`` JSON line, a
``BENCH_r0N.json`` driver wrapper, or a ``BENCH_MEASURED*.json`` headline)
against the repo's committed measurement history (``BENCH_MEASURED*.json``,
``BENCH_r0*.json``, ``records/**/*.json``) and emits ONE structured verdict
line::

    {"verdict": "PASS|REGRESSED|STALE|NO_BASELINE", ...}

Records carrying latency-percentile fields (``p50``/``p95``/``p99`` at
top level or under ``percentiles`` — what transform bench records emit
from the serving quantile sketch) are judged **per percentile** against
the same percentile in the history, and the overall verdict is the worst
sub-verdict (tail regressions cannot hide behind a healthy mean).

Verdicts:

* **PASS** — value within (or better than) the noise band around the
  comparable baseline (same metric, same platform).
* **REGRESSED** — value worse than the band. Exit 1.
* **STALE** — the record is NOT comparable to the best-known baseline: a
  CPU fallback run (``fallback_reason`` / a ``best_known_chip_record``
  marked stale) or a platform mismatch against a chip-measured history.
  This is the r05 situation — a wedged tunnel must read as "chip baseline
  is stale", never as a 679× regression. Exit 2.
* **NO_BASELINE** — no history for this metric at all. Exit 3.

The noise band is ``max(--tolerance, 2·MAD/median)`` over the historical
values for (metric, platform): single-sample histories fall back to the
tolerance (default 15% — measured round-to-round jitter on the chip
records), multi-sample histories widen to the observed spread.

Usage::

    python scripts/perf_sentinel.py BENCH_r05.json
    python scripts/perf_sentinel.py record.json --tolerance 0.1
    some_bench | python scripts/perf_sentinel.py -
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_robust():
    """The shared robust-statistics module (``obs/robust.py``), loaded
    BY PATH: the sentinel and the online anomaly detectors must use
    the same MAD/noise-band arithmetic, but judging a JSON record must
    not import the package (and with it jax)."""
    path = os.path.join(REPO, "spark_rapids_ml_tpu", "obs", "robust.py")
    spec = importlib.util.spec_from_file_location(
        "sparkml_obs_robust", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


_robust = _load_robust()

EXIT_CODES = {"PASS": 0, "REGRESSED": 1, "STALE": 2, "NO_BASELINE": 3}
DEFAULT_TOLERANCE = 0.15
PERCENTILE_KEYS = ("p50", "p95", "p99")


# -- record extraction -----------------------------------------------------


def _is_record(obj) -> bool:
    return (isinstance(obj, dict) and "metric" in obj
            and (obj.get("value") is not None
                 or record_percentiles(obj)))


def record_percentiles(record) -> Dict[str, float]:
    """The latency-percentile fields of a record: a ``percentiles`` dict
    and/or top-level ``p50``/``p95``/``p99`` keys (sketch-quantile output
    from instrumented transform benches)."""
    if not isinstance(record, dict):
        return {}
    out: Dict[str, float] = {}
    nested = record.get("percentiles")
    if isinstance(nested, dict):
        for key in PERCENTILE_KEYS:
            value = nested.get(key)
            if value is not None:
                try:
                    out[key] = float(value)
                except (TypeError, ValueError):
                    continue  # one malformed field never kills the run
    for key in PERCENTILE_KEYS:
        value = record.get(key)
        if value is not None:
            try:
                out[key] = float(value)
            except (TypeError, ValueError):
                continue
    return out


def extract_record(obj) -> Optional[Dict[str, Any]]:
    """The measurement record inside any of the repo's bench artifact
    shapes: a raw record, a BENCH_rN driver wrapper ({"parsed": ...}),
    or a BENCH_MEASURED composite ({"headline": ...})."""
    if _is_record(obj):
        return obj
    if isinstance(obj, dict):
        for key in ("parsed", "headline"):
            inner = obj.get(key)
            if _is_record(inner):
                return inner
    return None


def load_candidate(path: str) -> Dict[str, Any]:
    text = sys.stdin.read() if path == "-" else open(path).read()
    try:
        rec = extract_record(json.loads(text))
        if rec is not None:
            return rec
    except ValueError:
        pass
    # JSON-lines: last parseable record wins (emit_record's final-line
    # contract)
    rec = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            parsed = extract_record(json.loads(line))
        except ValueError:
            continue
        if parsed is not None:
            rec = parsed
    if rec is None:
        raise SystemExit(f"no bench record found in {path!r}")
    return rec


# -- history ---------------------------------------------------------------


def iter_history(root: str, exclude: Optional[str] = None
                 ) -> List[Dict[str, Any]]:
    """Every committed measurement record, tagged with its source file."""
    out: List[Dict[str, Any]] = []
    exclude_real = os.path.realpath(exclude) if exclude else None

    def _add(obj, source, when=None):
        rec = extract_record(obj)
        if rec is not None:
            entry = dict(rec)
            entry["_source"] = source
            if when and "measured_utc" not in entry:
                entry["_measured_utc"] = when
            out.append(entry)

    paths = sorted(glob.glob(os.path.join(root, "BENCH_MEASURED*.json")))
    paths += sorted(glob.glob(os.path.join(root, "BENCH_r[0-9]*.json")))
    for path in paths:
        if exclude_real and os.path.realpath(path) == exclude_real:
            continue
        try:
            doc = json.load(open(path))
        except ValueError:
            continue
        rel = os.path.relpath(path, root)
        when = doc.get("collected_utc") if isinstance(doc, dict) else None
        if isinstance(doc, dict):
            _add(doc, rel, when)
            # BENCH_MEASURED composites: every named sub-record counts
            for key, val in doc.items():
                if key in ("parsed", "headline"):
                    continue
                if _is_record(val):
                    _add(val, f"{rel}#{key}", when)
    for path in sorted(glob.glob(os.path.join(root, "records", "**",
                                              "*.json"), recursive=True)):
        if exclude_real and os.path.realpath(path) == exclude_real:
            continue
        rel = os.path.relpath(path, root)
        try:
            lines = open(path).read().splitlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                _add(json.loads(line), rel)
            except ValueError:
                continue
    return out


# -- verdict logic ---------------------------------------------------------


def higher_is_better(record: Dict[str, Any]) -> bool:
    # An explicit flag beats the text heuristic — percentile pseudo-records
    # force lower-is-better even when the metric NAME contains "/sec".
    explicit = record.get("higher_is_better")
    if isinstance(explicit, bool):
        return explicit
    text = f"{record.get('unit', '')} {record.get('metric', '')}".lower()
    if "budget_remaining" in text:
        # SLO error budget left: more is better, despite lacking a "/sec"
        # unit — and despite any "seconds"-flavored unit text (a budget
        # can be expressed as seconds of allowed badness remaining).
        return True
    if "burn_rate" in text:
        # SLO burn rate: budget spend speed — lower is better, and the
        # throughput-style default would invert the verdict.
        return False
    if "rows/sec" in text or "/sec" in text:
        return True
    if "second" in text:
        return False
    return True  # throughput-style by default


def _median(values: List[float]) -> float:
    return _robust.median(values)


def noise_band(values: List[float], tolerance: float) -> float:
    """Relative half-width of the acceptance band around the median —
    THE shared arithmetic (``obs/robust.py``): the offline sentinel
    and the online anomaly detectors judge against the same band."""
    return _robust.noise_band(values, tolerance)


def backend_mismatch_reason(record: Dict[str, Any]) -> Optional[str]:
    """Why this record's RESOLVED backend (the ``backend`` provenance
    stamp ``emit_record`` adds) disagrees with the backend it was
    supposed to run on — None when provenance is absent (older records)
    or consistent. A mismatch means the number itself is untrustworthy,
    which is a different failure from a slow-but-honest measurement."""
    resolved = (record.get("backend") or {}).get("platform")
    if not resolved:
        return None
    resolved = str(resolved).lower()
    required = record.get("required_platform")
    if required and resolved != str(required).lower():
        return (f"record required platform {required!r} but the resolved "
                f"jax backend was {resolved!r}")
    claimed = record.get("platform")
    if claimed and str(claimed).lower() != resolved:
        return (f"record claims platform {claimed!r} but the resolved jax "
                f"backend was {resolved!r} (silent fallback)")
    return None


def _is_fallback(record: Dict[str, Any]) -> bool:
    if record.get("fallback_reason"):
        return True
    best = record.get("best_known_chip_record")
    return bool(isinstance(best, dict) and best.get("stale"))


def _parse_utc(value) -> Optional[float]:
    """Epoch seconds from an ISO-8601 UTC stamp (``...Z`` or offset
    spelled out); None when unparseable — a malformed timestamp must
    never break a verdict."""
    if not value:
        return None
    from datetime import datetime, timezone

    text = str(value).strip()
    if text.endswith("Z"):
        text = text[:-1] + "+00:00"
    try:
        dt = datetime.fromisoformat(text)
    except ValueError:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


def stale_baseline_age_days(stale_baseline,
                            now: Optional[float] = None
                            ) -> Optional[float]:
    """How many days old the stale chip baseline is — the number that
    turns 'STALE' from prose into an actionable age. None when the
    baseline carries no parseable measurement timestamp."""
    if not isinstance(stale_baseline, dict):
        return None
    measured = _parse_utc(stale_baseline.get("measured_utc"))
    if measured is None:
        return None
    if now is None:
        import time as _time

        now = _time.time()
    return max((now - measured) / 86400.0, 0.0)


def judge(record: Dict[str, Any], history: List[Dict[str, Any]],
          tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """The sentinel verdict for one record against the history."""
    metric = record.get("metric")
    platform = record.get("platform")
    value = float(record["value"])
    # percentile-only history entries carry no scalar value to compare
    same_metric = [h for h in history
                   if h.get("metric") == metric
                   and h.get("value") is not None]
    verdict: Dict[str, Any] = {
        "metric": metric,
        "value": value,
        "unit": record.get("unit"),
        "platform": platform,
    }

    if not same_metric:
        verdict.update(
            verdict="NO_BASELINE",
            reason=f"no committed history for metric {metric!r}",
        )
        return verdict

    chip_history = [h for h in same_metric
                    if h.get("platform") not in (None, "cpu")]
    if _is_fallback(record) or (
        platform == "cpu" and chip_history
    ):
        # The r04/r05 situation: a fallback (or platform-mismatched) run
        # can NEVER regress or clear a chip baseline — the baseline is
        # stale, which is its own first-class state.
        pick = max if higher_is_better(record) else min
        best = pick(chip_history, key=lambda h: float(h["value"]),
                    default=None) if chip_history else None
        stale_baseline = record.get("best_known_chip_record") or (
            {
                "value": float(best["value"]),
                "platform": best.get("platform"),
                "source": best.get("_source"),
                "measured_utc": best.get("measured_utc")
                or best.get("_measured_utc"),
            } if best else None
        )
        verdict.update(
            verdict="STALE",
            reason=(
                f"record is a {platform or 'non-chip'} fallback run "
                f"({record.get('fallback_reason') or 'platform mismatch'}); "
                "the chip baseline is stale, not regressed — re-measure on "
                "the chip before trusting either number"
            ),
            stale_baseline=stale_baseline,
        )
        # The r04+ situation surfaced as a NUMBER, not prose: every
        # STALE verdict states how long the chip baseline has gone
        # un-re-measured while rounds fall back to CPU.
        age_days = stale_baseline_age_days(stale_baseline)
        if age_days is not None:
            verdict["stale_baseline_age_days"] = round(age_days, 2)
            cause = (
                "this round's device tunnel fell back to CPU"
                if record.get("fallback_reason")
                else f"this round ran on {platform or 'another platform'}"
            )
            verdict["stale_warning"] = (
                f"chip baseline is {age_days:.1f} days old and {cause} "
                "— the committed chip numbers have not been re-measured "
                "since; treat every chip-derived claim as aging"
            )
        return verdict

    # Untagged history (older records without a platform field — the r04
    # bench_models/gram_sweep lines were all chip runs) counts as
    # comparable for accelerator candidates; CPU candidates only ever
    # compare against explicitly-CPU history.
    if platform == "cpu":
        comparable = [h for h in same_metric if h.get("platform") == "cpu"]
    else:
        comparable = [h for h in same_metric
                      if h.get("platform") in (platform, None)]
    if not comparable:
        verdict.update(
            verdict="NO_BASELINE",
            reason=(
                f"history for {metric!r} exists only on other platforms "
                f"({sorted({h.get('platform') for h in same_metric})})"
            ),
        )
        return verdict

    values = [float(h["value"]) for h in comparable]
    center = _median(values)
    band = noise_band(values, tolerance)
    hib = higher_is_better(record)
    floor = center * (1.0 - band)
    ceil = center * (1.0 + band)
    ratio = value / center if center else None
    baseline = {
        "value": center,
        "n_samples": len(values),
        "sources": sorted({h.get("_source") for h in comparable})[:8],
        "platform": platform,
    }
    verdict.update(
        baseline=baseline,
        band={"relative": round(band, 4), "low": floor, "high": ceil},
        ratio=round(ratio, 4) if ratio is not None else None,
        higher_is_better=hib,
    )
    regressed = value < floor if hib else value > ceil
    if regressed:
        verdict.update(
            verdict="REGRESSED",
            reason=(
                f"value {value:g} is {'below' if hib else 'above'} the "
                f"noise band ({floor:g} .. {ceil:g}) around the "
                f"{len(values)}-sample baseline median {center:g}"
            ),
        )
    else:
        verdict.update(
            verdict="PASS",
            reason=(
                f"value {value:g} is within/beyond the noise band "
                f"({floor:g} .. {ceil:g}) of baseline median {center:g}"
            ),
        )
    return verdict


def _combine_verdicts(kinds) -> str:
    """Worst-wins fold over sub-verdicts: a tail regression can never hide
    behind a healthy mean; NO_BASELINE only when nothing was comparable."""
    for kind in ("REGRESSED", "STALE"):
        if kind in kinds:
            return kind
    if "PASS" in kinds:
        return "PASS"
    return "NO_BASELINE"


def judge_percentiles(record: Dict[str, Any],
                      history: List[Dict[str, Any]],
                      tolerance: float = DEFAULT_TOLERANCE
                      ) -> Dict[str, Any]:
    """Per-percentile verdicts for a record carrying p50/p95/p99 fields.

    Each percentile is judged by ``judge`` against the SAME percentile of
    history records for the metric (a p99 only ever compares to p99s);
    the scalar ``value``, when also present, is judged as before. The
    overall verdict is the worst sub-verdict.
    """
    pcts = record_percentiles(record)
    sub: Dict[str, Dict[str, Any]] = {}
    carry = {
        k: record[k]
        for k in ("platform", "fallback_reason", "best_known_chip_record")
        if k in record
    }
    # Latency percentiles are always lower-is-better, even when the
    # record's scalar unit (or its metric NAME) says rows/sec; the
    # pseudo-records carry an explicit direction, immune to the text
    # heuristic.
    pct_unit = record.get("percentile_unit") or "seconds"
    for key, value in pcts.items():
        pseudo = dict(carry)
        pseudo.update(metric=record.get("metric"), value=value,
                      unit=pct_unit, higher_is_better=False)
        pseudo_history = []
        for h in history:
            if h.get("metric") != record.get("metric"):
                continue
            h_pcts = record_percentiles(h)
            if key not in h_pcts:
                continue
            entry = dict(h)
            entry["value"] = h_pcts[key]
            pseudo_history.append(entry)
        sub[key] = judge(pseudo, pseudo_history, tolerance=tolerance)
    verdicts = list(sub.values())
    if record.get("value") is not None:
        scalar = judge(record, [h for h in history
                                if h.get("value") is not None],
                       tolerance=tolerance)
        verdicts.append(scalar)
    else:
        scalar = None
    overall = _combine_verdicts({v["verdict"] for v in verdicts})
    reason_parts = [f"{key}: {v['verdict']}" for key, v in sub.items()]
    if scalar is not None:
        reason_parts.append(f"scalar: {scalar['verdict']}")
    out: Dict[str, Any] = {
        "metric": record.get("metric"),
        "unit": record.get("unit"),
        "platform": record.get("platform"),
        "verdict": overall,
        "percentiles": sub,
        "reason": "; ".join(reason_parts),
    }
    if scalar is not None:
        out["scalar"] = scalar
        out["value"] = record.get("value")
    return out


def judge_record(record: Dict[str, Any], history: List[Dict[str, Any]],
                 tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Dispatch: percentile-aware judging when the record carries
    latency-percentile fields, scalar judging otherwise. A record whose
    backend provenance contradicts its declared/required platform is
    judged STALE with ``reason_code: backend_mismatch`` before any
    number comparison — the measurement itself is untrustworthy, and
    the live-side watchdog raises the same condition as the
    ``fit_backend_degraded`` incident."""
    mismatch = backend_mismatch_reason(record)
    if mismatch:
        return {
            "metric": record.get("metric"),
            "value": record.get("value"),
            "unit": record.get("unit"),
            "platform": record.get("platform"),
            "verdict": "STALE",
            "reason_code": "backend_mismatch",
            "incident": "fit_backend_degraded",
            "reason": (
                f"{mismatch} — the number was measured on the wrong "
                "backend; the comparable baseline is stale, not regressed "
                "(live side raises incident fit_backend_degraded)"
            ),
        }
    if record_percentiles(record):
        return judge_percentiles(record, history, tolerance=tolerance)
    return judge(record, history, tolerance=tolerance)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("record", help="record file (or '-' for stdin): an "
                        "emit_record line, BENCH_rN wrapper, or "
                        "BENCH_MEASURED composite")
    parser.add_argument("--history-root", default=REPO,
                        help="repo root holding BENCH_MEASURED*/records/ "
                        "(default: this repo)")
    parser.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                        help="minimum relative noise band (default 0.15)")
    parser.add_argument("--indent", type=int, default=None,
                        help="pretty-print the verdict JSON")
    args = parser.parse_args(argv)

    record = load_candidate(args.record)
    exclude = None if args.record == "-" else args.record
    history = iter_history(args.history_root, exclude=exclude)
    verdict = judge_record(record, history, tolerance=args.tolerance)
    print(json.dumps(verdict, indent=args.indent, default=str))
    return EXIT_CODES[verdict["verdict"]]


if __name__ == "__main__":
    sys.exit(main())
