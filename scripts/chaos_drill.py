#!/usr/bin/env python
"""Chaos drill: run the fault matrix against a live serve server.

Stands up the real stack — fitted PCA model, registry, engine with
retries + breaker + degraded CPU fallback, stdlib HTTP server — then
attacks it through the fault-injection plane (``serve.faults``), one
fault class at a time, measuring what a client on the wire experiences:

* **baseline**   — no faults: availability must be 1.0;
* **raise**      — 100% backend errors: the breaker opens, traffic
  degrades to the CPU fallback, availability stays high;
* **stall**      — a transform wedges past the worker budget: the
  watchdog fails it fast (``WorkerCrashed`` → 503), the worker
  restarts, traffic continues;
* **nan**        — corrupted outputs: the NaN guard converts poison
  into retryable errors;
* **latency**    — +spike on every call: answers stay correct, the SLO
  latency burn shows it;
* **overload**   — closed-loop 2x+ traffic from a greedy batch tenant
  with a tiny quota alongside a compliant interactive tenant (while a
  latency fault plays "the device is the bottleneck"): the compliant
  tenant keeps its availability, the adaptive controller sheds the
  greedy excess, the queue-depth detector opens (and auto-resolves) an
  incident, and the breaker stays CLOSED throughout — overload must
  never read as backend failure (the PR 6 invariant extended to the
  admission/shed layer);
* **recovery**   — faults cleared: a half-open probe closes the
  breaker and availability returns to 1.0;
* **canary_rollback** — train-while-serving (own subprocess): a
  streaming-fit candidate version canaries a slice of live alias
  traffic, a fault targeted at the CANDIDATE VERSION fires, the
  incumbent's traffic stays at availability 1.0, the rollout
  controller auto-rolls the alias back within the detector window,
  and exactly one ``serve_canary_regressed`` incident (labels naming
  the candidate version, complete bundle) opens and auto-resolves.

The drill also asserts the **auto-incident loop** (``obs.incidents``,
installed on the sampler by the serve server): each injected fault
class must open EXACTLY ONE deduped incident from its expected detector
(``raise``/``stall``/``nan`` → ``serve_error_rate``, ``latency`` →
``serve_p99_spike``) with an evidence bundle on disk (incident +
implicated-series history + a flight dump), and that incident must
auto-resolve after the fault clears. The drill compresses the loop via
env knobs set below (100 ms sampling cadence, 8 s detector windows,
1 s reopen cooldown) — the same engine, just faster.

Every request gets exactly one terminal outcome (the drill exits 1 if
any hangs past its client timeout, if availability under fault drops
below ``SPARKML_CHAOS_MIN_AVAILABILITY``, default 0.5, or if any
fault class fails its incident contract), and the drill emits ONE
``bench_common.emit_record`` line the perf sentinel can judge against
committed history:

* ``availability_baseline`` / ``availability_under_fault`` /
  ``availability_recovery`` — fraction of requests answered 200
  (degraded answers count: the service answered);
* ``degraded_served``       — how many answers came from the CPU
  fallback;
* ``breaker_open_seconds``  — how long the breaker was open during the
  drill (lower = faster recovery);
* ``recovery_seconds``      — fault cleared → breaker closed again;
* ``incidents_opened`` / ``incidents_resolved`` — auto-incident totals
  over the drill (opened counts everything the detectors saw,
  including cross-cutting ones like breaker flaps or SLO fast-burn).

Knobs (env): SPARKML_CHAOS_REQUESTS (per phase, default 24),
SPARKML_CHAOS_FEATURES (16), SPARKML_CHAOS_K (4).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

# Compress the detect→diagnose→resolve loop BEFORE the package is
# imported (the engine reads these at construction): 100 ms sampling
# into a 100 ms-resolution history tier (the default 1 s tier would
# quantize the 100 ms cadence right back to one point per second),
# 8 s detector windows, 2-sweep hysteresis, 1 s reopen cooldown, and no
# incident-triggered profile captures (the drill hammers the backend —
# a capture here would only add noise to the thing being measured).
os.environ.setdefault("SPARK_RAPIDS_ML_TPU_OBS_SAMPLE_MS", "100")
os.environ.setdefault("SPARK_RAPIDS_ML_TPU_OBS_HISTORY",
                      "0.1x120,1x600")
os.environ.setdefault("SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_WINDOW_S", "8")
os.environ.setdefault("SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_OPEN_AFTER", "2")
os.environ.setdefault(
    "SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_RESOLVE_AFTER", "3")
os.environ.setdefault("SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_COOLDOWN_S", "1")
os.environ.setdefault("SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_CAPTURE_S", "0")
# The overload phase: the greedy tenant gets a deliberately tiny quota
# (closed-loop flood is ~10x over it) and the shed controller reacts to
# queue wait at drill scale. Other phases use the default tenant
# (interactive, unlimited quota), which the controller never sheds —
# these knobs change nothing for them.
os.environ.setdefault("SPARK_RAPIDS_ML_TPU_SERVE_TENANT_QUOTAS",
                      "chaos_greedy:30:30")
os.environ.setdefault("SPARK_RAPIDS_ML_TPU_SERVE_SHED_QUEUE_WAIT_MS",
                      "200")

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench_common  # noqa: E402 (scripts/ on path when run directly)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _post_predict(base: str, model: str, rows, timeout: float = 15.0,
                  tenant: str = "", priority: str = ""):
    """One HTTP predict; returns (status, payload_dict). Never raises —
    a drill request that cannot be categorized is itself a finding."""
    body = json.dumps({"model": model, "rows": rows.tolist()}).encode()
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers["X-Tenant"] = tenant
    if priority:
        headers["X-Priority"] = priority
    req = urllib.request.Request(
        f"{base}/predict", data=body, headers=headers,
    )
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        try:
            payload = json.loads(exc.read())
        except ValueError:
            payload = {}
        return exc.code, payload
    except Exception as exc:  # noqa: BLE001 - hang/reset IS the result
        return 0, {"error": f"{type(exc).__name__}: {exc}"}


def _get_json(base: str, path: str, timeout: float = 10.0) -> dict:
    try:
        resp = urllib.request.urlopen(f"{base}{path}", timeout=timeout)
        return json.loads(resp.read())
    except Exception:  # noqa: BLE001 - a dead ops endpoint IS a finding
        return {}


def _incident_entries(doc: dict, detector: str) -> list:
    return [i for i in (doc.get("open", []) + doc.get("recent", []))
            if i.get("detector") == detector]


def _await_new_incidents(base: str, detector: str, known_ids: set,
                         budget: float = 15.0) -> list:
    """Poll ``/debug/incidents`` until the detector grows a NEW
    incident (then one more beat to catch a dedup failure); returns
    every new entry seen."""
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        doc = _get_json(base, "/debug/incidents")
        new = [i for i in _incident_entries(doc, detector)
               if i.get("id") not in known_ids]
        if new:
            # one more detector cadence: continued firing must UPDATE
            # the incident, not open a sibling
            time.sleep(1.0)
            doc = _get_json(base, "/debug/incidents")
            return [i for i in _incident_entries(doc, detector)
                    if i.get("id") not in known_ids]
        time.sleep(0.2)
    return []


def _await_resolved(base: str, incident_id: str,
                    budget: float = 30.0) -> bool:
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        doc = _get_json(base, "/debug/incidents")
        for entry in doc.get("recent", []):
            if (entry.get("id") == incident_id
                    and entry.get("state") == "resolved"):
                return True
        time.sleep(0.2)
    return False


REPLICA_DRAIN_PREFIX = "REPLICA_DRAIN_RESULT "


def replica_drain_child() -> int:
    """The replica-drain drill leg, run in its OWN process with 2
    forced host devices (device count is fixed at jax init — the main
    drill stays a faithful single-device rehearsal).

    Contract (ISSUE 13): fault ONE device's replica → availability
    >= 0.99 via the surviving replica (retries + placement drain),
    exactly one ``serve_replica_degraded`` incident opens with a
    complete evidence bundle and auto-resolves, and the drained replica
    re-enters after its half-open probe succeeds."""
    import concurrent.futures

    import jax

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        ServeEngine,
        fault_plane,
        start_serve_server,
    )

    result = {"devices": len(jax.devices())}
    rng = np.random.default_rng(13)
    x = rng.normal(size=(1024, 16))
    model = PCA().setK(4).fit(x)
    registry = ModelRegistry()
    registry.register("drill_replica_pca", model, buckets=(16, 64))
    # retries=3 covers the drain threshold (3): the ISSUE 15
    # small-request concentration pins the idle tier (and its retries)
    # to the SAME replica until its health trips, so the first faulted
    # request's surviving attempt is the fourth
    engine = ServeEngine(
        registry, max_batch_rows=64, max_wait_ms=1.0,
        retries=3, backoff_ms=10, breaker_failures=8,
        default_deadline_ms=10_000, replicas=2,
    )
    engine.warmup("drill_replica_pca")
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    plane = fault_plane()
    try:
        rset = engine._replicas[("drill_replica_pca", 1)]
        # replica 0: the concentration target — a fault targeted at the
        # spread-to sibling would never fire on light serial traffic
        victim = rset.replicas[0]
        victim.health.cooldown_seconds = 1.0
        result["victim_device"] = victim.label
        doc = _get_json(base, "/debug/incidents")
        known = {i.get("id") for i in
                 _incident_entries(doc, "serve_replica_degraded")}
        plane.inject("drill_replica_pca", "raise", count=None,
                     device=victim.label)

        statuses = []

        def one(i: int) -> None:
            n = int(rng.integers(1, 9))
            start = int(rng.integers(0, x.shape[0] - n))
            status, _payload = _post_predict(
                base, "drill_replica_pca", x[start:start + n])
            statuses.append(status)

        with concurrent.futures.ThreadPoolExecutor(6) as pool:
            list(pool.map(one, range(80)))
        ok = sum(1 for s in statuses if s == 200)
        result["requests"] = len(statuses)
        result["availability"] = ok / len(statuses)
        result["hung"] = sum(1 for s in statuses if s == 0)
        result["victim_state_under_fault"] = victim.state()
        result["breaker_state"] = engine.breaker_snapshot()[
            "drill_replica_pca"]["state"]

        new = _await_new_incidents(base, "serve_replica_degraded",
                                   known)
        result["incidents_opened"] = len(new)
        problems = []
        if len(new) != 1:
            problems.append(
                f"expected exactly 1 serve_replica_degraded incident, "
                f"saw {len(new)}")
        for incident in new:
            problems.extend(_bundle_problems(incident))

        # recovery: the fault clears, the half-open probe re-enters
        plane.clear()
        deadline = time.monotonic() + 20.0
        while (victim.state() != "serving"
               and time.monotonic() < deadline):
            time.sleep(0.2)
            n = int(rng.integers(1, 9))
            start = int(rng.integers(0, x.shape[0] - n))
            _post_predict(base, "drill_replica_pca",
                          x[start:start + n])
        result["reentered"] = victim.state() == "serving"
        if not result["reentered"]:
            problems.append("drained replica never re-entered")
        resolved = all(
            _await_resolved(base, incident["id"]) for incident in new)
        result["incidents_resolved"] = resolved
        if new and not resolved:
            problems.append("replica incident did not auto-resolve")
        result["problems"] = problems
    finally:
        plane.clear()
        server.shutdown()
        engine.shutdown()
        from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

        tsdb_mod.get_sampler().stop()
        time.sleep(1.0)
    sys.stdout.write(REPLICA_DRAIN_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0 if not result.get("problems") else 1


AUTOSCALE_FLAP_PREFIX = "AUTOSCALE_FLAP_RESULT "


def autoscale_flap_child() -> int:
    """The autoscale anti-flap drill leg, run in its OWN process with 4
    forced host devices.

    Contract (ISSUE 15): under a load square-wave OSCILLATING faster
    than the hysteresis hold, the controller must not flap — no two
    scale actions land closer than the cooldown, every request keeps
    answering 200, the breaker stays closed, and a deliberate
    scale-down never opens a ``serve_replica_degraded`` incident (a
    retired replica is an operator decision, not a sick device —
    exactly the incident-dedup discipline the other phases keep)."""
    import jax

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        AutoscaleController,
        ModelRegistry,
        ServeEngine,
        fault_plane,
        start_serve_server,
    )

    result = {"devices": len(jax.devices())}
    rng = np.random.default_rng(31)
    x = rng.normal(size=(1024, 16))
    model = PCA().setK(4).fit(x)
    registry = ModelRegistry()
    registry.register("flap_pca", model, buckets=(64, 256))
    engine = ServeEngine(registry, max_batch_rows=256, max_wait_ms=1.0,
                         max_queue_depth=256,
                         default_deadline_ms=15_000)
    engine.warmup("flap_pca")
    engine.scale_replicas(1)
    # the modeled per-batch device time that makes capacity
    # replica-bound (the multidevice phases' CPU-CI honesty device)
    fault_plane().inject("flap_pca", "latency", count=None,
                         seconds=0.04)
    controller = AutoscaleController(
        engine, min_replicas=1, max_replicas=4, interval_s=0.2,
        up_queue_wait_s=0.05, up_hold_s=0.4, down_hold_s=1.0,
        cooldown_s=2.0, down_queue_wait_s=0.03, down_occupancy=0.6,
    )
    controller.start()
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    statuses = []
    try:
        doc = _get_json(base, "/debug/incidents")
        known = {i.get("id") for i in
                 _incident_entries(doc, "serve_replica_degraded")}
        # the square wave: ~1.2 s SATURATING burst (6 closed-loop
        # threads of full-bucket requests — several times the
        # 1-replica capacity), ~1.2 s silence — a period far shorter
        # than down_hold + cooldown, so a naive controller would flap
        # every cycle
        import concurrent.futures

        lock = threading.Lock()

        def _burst_client(worker: int, edge: float) -> None:
            # per-task rng: shared numpy Generators across threads can
            # corrupt draws into bad request shapes (the _tenant_burst
            # lesson)
            wrng = np.random.default_rng(1000 + worker)
            while time.monotonic() < edge:
                start = int(wrng.integers(0, x.shape[0] - 256))
                status, _payload = _post_predict(
                    base, "flap_pca", x[start:start + 256],
                    timeout=30.0)
                with lock:
                    statuses.append(status)

        stop_at = time.monotonic() + 14.0
        burst = True
        cycle = 0
        with concurrent.futures.ThreadPoolExecutor(6) as pool:
            while time.monotonic() < stop_at:
                edge = min(time.monotonic() + 1.2, stop_at)
                if burst:
                    cycle += 1
                    list(pool.map(
                        lambda w: _burst_client(w + 6 * cycle, edge),
                        range(6)))
                else:
                    time.sleep(max(edge - time.monotonic(), 0.0))
                burst = not burst
        ok = sum(1 for s in statuses if s == 200)
        result["requests"] = len(statuses)
        result["availability"] = ok / len(statuses) if statuses else 0.0
        result["hung"] = sum(1 for s in statuses if s == 0)
        history = controller.decision_history()
        actions = [h for h in history
                   if h["decision"] in ("scale_up", "scale_down")]
        gaps = [round(b["at"] - a["at"], 3)
                for a, b in zip(actions, actions[1:])]
        result["scale_actions"] = [
            {"decision": h["decision"], "from": h["from"],
             "to": h["to"]} for h in actions]
        result["action_gaps_s"] = gaps
        result["cooldown_s"] = controller.cooldown_s
        result["breaker_state"] = engine.breaker_snapshot().get(
            "flap_pca", {}).get("state", "closed")
        new = [i for i in _incident_entries(
            _get_json(base, "/debug/incidents"),
            "serve_replica_degraded") if i.get("id") not in known]
        result["replica_incidents"] = len(new)
        problems = []
        if not actions:
            problems.append(
                "the oscillating load never drove a single scale "
                "action — the phase did not exercise the controller")
        bad = [g for g in gaps if g < controller.cooldown_s - 0.05]
        if bad:
            problems.append(
                f"scale actions {bad} s apart — flapping faster than "
                f"the {controller.cooldown_s} s cooldown")
        if result["availability"] < 0.99:
            problems.append(
                f"availability {result['availability']:.3f} < 0.99 "
                "under the oscillating load")
        if result["hung"]:
            problems.append(f"{result['hung']} request(s) hung")
        if result["breaker_state"] != "closed":
            problems.append(
                "breaker opened under pure load oscillation")
        if new:
            problems.append(
                f"{len(new)} serve_replica_degraded incident(s) opened "
                "by deliberate scale-downs — retirement must never "
                "page as a sick device")
        result["problems"] = problems
    finally:
        fault_plane().clear()
        controller.stop()
        server.shutdown()
        engine.shutdown()
        from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

        tsdb_mod.get_sampler().stop()
        time.sleep(1.0)
    sys.stdout.write(AUTOSCALE_FLAP_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0 if not result.get("problems") else 1


def run_autoscale_flap_phase() -> dict:
    """Spawn the 4-device autoscale-flap child; returns its result (or
    a synthesized failure entry when the child broke)."""
    import subprocess

    env = dict(os.environ)
    env["SPARKML_CHAOS_PHASE"] = "autoscale_flap_child"
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["XLA_FLAGS"] = bench_common.force_device_count_flags(4)
    env.pop("SPARK_RAPIDS_ML_TPU_SERVE_REPLICAS", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=420,
    )
    result = bench_common.prefixed_result(proc.stdout,
                                          AUTOSCALE_FLAP_PREFIX)
    if result is None:
        return {"problems": [
            f"autoscale-flap child produced no result "
            f"(rc={proc.returncode}): {proc.stderr[-1500:]}"]}
    if proc.returncode != 0 and not result.get("problems"):
        result.setdefault("problems", []).append(
            f"autoscale-flap child exited {proc.returncode}")
    return result


CANARY_ROLLBACK_PREFIX = "CANARY_ROLLBACK_RESULT "


def canary_rollback_child() -> int:
    """The canary-rollback drill leg, run in its OWN process (fresh
    incident engine, fresh metrics, nothing shared with the main
    drill's detectors).

    Contract (ISSUE 14): stream-fit a candidate version while the
    incumbent serves, canary a slice of live alias traffic onto it,
    inject a fault targeted at the CANDIDATE VERSION ONLY → every
    incumbent-served request stays 200 (non-canary availability 1.0),
    the controller auto-rolls the alias back within the detector
    window, exactly one ``serve_canary_regressed`` incident opens with
    a complete evidence bundle whose labels name the candidate
    version, and the incident auto-resolves once the regressed gauge's
    hold elapses."""
    import concurrent.futures
    import tempfile

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        RolloutController,
        ServeEngine,
        StreamingTrainer,
        fault_plane,
        start_serve_server,
    )

    result = {}
    problems = []
    rng = np.random.default_rng(14)
    n_features, k = 12, 3
    x = rng.normal(size=(1024, n_features))
    incumbent_model = PCA().setK(k).fit(x)
    registry = ModelRegistry()
    registry.register("canary_pca", incumbent_model, buckets=(16, 64))
    # The model-level breaker stays OUT of this phase's way (huge
    # failure threshold, burn trip disabled): the actuator under test
    # is the ROLLOUT controller — a canary storm must be answered by an
    # alias rollback, not by the incumbent's breaker opening.
    engine = ServeEngine(
        registry, max_batch_rows=64, max_wait_ms=1.0,
        retries=1, backoff_ms=5,
        breaker_failures=1000, breaker_burn_threshold=0,
        default_deadline_ms=10_000,
    )
    rollout = RolloutController(
        engine, "canary_pca", alias="canary_prod",
        fraction=0.35, shadow_tenant="canary_shadow",
        min_requests=8, window_s=30.0, eval_interval_s=0.1,
        burn_threshold=14.4, availability_target=0.99,
        regressed_hold_s=3.0,
    )
    engine.attach_rollout(rollout)
    rollout.promote(1)  # initial deploy: warm, then pin the alias
    trainer = StreamingTrainer(
        registry, "canary_pca", n_features, k,
        batches_per_version=4,
        artifact_dir=tempfile.mkdtemp(prefix="sparkml_canary_drill_"),
        rollout=rollout,
    )
    # live-traffic shape: the trainer streams the SAME distribution the
    # incumbent was fitted on, so the candidate is numerically honest —
    # the injected fault, not the model, is what burns the canary
    for i in range(4):
        trainer.feed(x[i * 256:(i + 1) * 256])
    result["candidate"] = rollout.candidate
    if rollout.candidate is None:
        problems.append("streaming trainer never published a candidate")
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    plane = fault_plane()
    try:
        doc = _get_json(base, "/debug/incidents")
        known = {i.get("id") for i in
                 _incident_entries(doc, "serve_canary_regressed")}
        rollout.start_canary()
        candidate = rollout.canary_version
        result["canary_version"] = candidate
        plane.inject("canary_pca", "raise", count=None,
                     version=candidate)

        import threading

        outcomes = []
        lock = threading.Lock()

        def one(i: int) -> None:
            # per-task generator: numpy Generators are not thread-safe,
            # and a corrupted draw could slice a bad request shape that
            # reads as an incumbent failure (the _tenant_burst lesson)
            local_rng = np.random.default_rng(2000 + i)
            n = int(local_rng.integers(1, 9))
            start = int(local_rng.integers(0, x.shape[0] - n))
            status, payload = _post_predict(
                base, "canary_prod", x[start:start + n])
            with lock:
                outcomes.append((status, payload.get("version")))

        with concurrent.futures.ThreadPoolExecutor(6) as pool:
            list(pool.map(one, range(120)))

        incumbent_hits = [s for s, v in outcomes if v == 1]
        canary_hits = [s for s, v in outcomes if v == candidate]
        unattributed = [s for s, v in outcomes
                        if v not in (1, candidate)]
        result["requests"] = len(outcomes)
        result["incumbent_requests"] = len(incumbent_hits)
        result["canary_requests"] = len(canary_hits)
        result["canary_errors"] = sum(1 for s in canary_hits
                                      if s != 200)
        result["unattributed"] = len(unattributed)
        result["non_canary_availability"] = (
            sum(1 for s in incumbent_hits if s == 200)
            / len(incumbent_hits) if incumbent_hits else 0.0)
        if unattributed:
            problems.append(
                f"{len(unattributed)} response(s) carried no serving "
                "version (cannot attribute to an arm)")

        # rollback within the detector window: the controller judges at
        # its eval cadence as results stream in; give it a short grace
        # of trickle traffic in case the burst ended right at the floor
        deadline = time.monotonic() + 10.0
        while rollout.canary_active and time.monotonic() < deadline:
            n = int(rng.integers(1, 9))
            start = int(rng.integers(0, x.shape[0] - n))
            _post_predict(base, "canary_prod", x[start:start + n])
            time.sleep(0.05)
        decisions = list(rollout.decisions)
        rollbacks = [d for d in decisions if d["action"] == "rollback"]
        result["rolled_back"] = bool(rollbacks)
        result["rollback_reason"] = (rollbacks[0].get("reason")
                                     if rollbacks else None)
        if not rollbacks:
            problems.append(
                "canary never auto-rolled back under a candidate-"
                "targeted 100% fault")
        alias_entry = registry.resolve_entry("canary_prod")
        result["alias_version_after"] = alias_entry.version
        if alias_entry.version != 1:
            problems.append(
                f"alias points at v{alias_entry.version} after "
                "rollback (expected the incumbent v1)")

        # post-rollback: ALL alias traffic serves the incumbent at
        # availability 1.0 (the fault is still armed — it targets the
        # candidate version, which no longer sees traffic)
        post = []
        for _ in range(30):
            n = int(rng.integers(1, 9))
            start = int(rng.integers(0, x.shape[0] - n))
            status, payload = _post_predict(
                base, "canary_prod", x[start:start + n])
            post.append((status, payload.get("version")))
        result["post_rollback_availability"] = (
            sum(1 for s, _v in post if s == 200) / len(post))
        result["post_rollback_canary_hits"] = sum(
            1 for _s, v in post if v == candidate)
        if result["post_rollback_canary_hits"]:
            problems.append(
                "candidate still served alias traffic after rollback")

        new = _await_new_incidents(base, "serve_canary_regressed",
                                   known)
        result["incidents_opened"] = len(new)
        if len(new) != 1:
            problems.append(
                f"expected exactly 1 serve_canary_regressed incident, "
                f"saw {len(new)}")
        for incident in new:
            problems.extend(_bundle_problems(incident))
            named = str(incident.get("labels", {}).get("candidate"))
            if named != str(candidate):
                problems.append(
                    f"incident names candidate {named!r}, expected "
                    f"{candidate!r}")
        # the regressed gauge clears after its hold (ticked by rollout
        # polls), then the detector's quiet sweeps auto-resolve
        resolved = True
        for incident in new:
            inc_deadline = time.monotonic() + 30.0
            done = False
            while time.monotonic() < inc_deadline:
                _get_json(base, "/debug/rollout")  # ticks the hold
                if _await_resolved(base, incident["id"], budget=0.5):
                    done = True
                    break
            if not done:
                resolved = False
                problems.append(
                    f"{incident['id']} did not auto-resolve after the "
                    "regressed hold")
        result["incidents_resolved"] = resolved
        result["problems"] = problems
    finally:
        plane.clear()
        server.shutdown()
        engine.shutdown()
        from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

        tsdb_mod.get_sampler().stop()
        time.sleep(1.0)
    sys.stdout.write(CANARY_ROLLBACK_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0 if not result.get("problems") else 1


def run_canary_rollback_phase() -> dict:
    """Spawn the canary-rollback child; returns its result (or a
    synthesized failure entry when the child broke)."""
    import subprocess

    env = dict(os.environ)
    env["SPARKML_CHAOS_PHASE"] = "canary_rollback_child"
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=420,
    )
    result = bench_common.prefixed_result(proc.stdout,
                                          CANARY_ROLLBACK_PREFIX)
    if result is None:
        return {"problems": [
            f"canary-rollback child produced no result "
            f"(rc={proc.returncode}): {proc.stderr[-1500:]}"]}
    if proc.returncode != 0 and not result.get("problems"):
        result.setdefault("problems", []).append(
            f"canary-rollback child exited {proc.returncode}")
    return result


def run_replica_drain_phase() -> dict:
    """Spawn the 2-device replica-drain child; returns its result (or
    a synthesized failure entry when the child broke)."""
    import subprocess

    env = dict(os.environ)
    env["SPARKML_CHAOS_PHASE"] = "replica_drain_child"
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["XLA_FLAGS"] = bench_common.force_device_count_flags(2)
    env.pop("SPARK_RAPIDS_ML_TPU_SERVE_REPLICAS", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=420,
    )
    result = bench_common.prefixed_result(proc.stdout,
                                          REPLICA_DRAIN_PREFIX)
    if result is None:
        return {"problems": [
            f"replica-drain child produced no result "
            f"(rc={proc.returncode}): {proc.stderr[-1500:]}"]}
    if proc.returncode != 0 and not result.get("problems"):
        result.setdefault("problems", []).append(
            f"replica-drain child exited {proc.returncode}")
    return result


def _bundle_problems(incident: dict) -> list:
    """What's missing from one incident's on-disk evidence bundle."""
    problems = []
    evidence = incident.get("evidence") or {}
    directory = evidence.get("dir")
    if not directory or not os.path.isdir(directory):
        return [f"no evidence dir ({directory!r})"]
    for fname in ("incident.json", "history.json"):
        path = os.path.join(directory, fname)
        if not os.path.isfile(path):
            problems.append(f"missing {fname}")
    history_path = os.path.join(directory, "history.json")
    if os.path.isfile(history_path):
        try:
            with open(history_path) as f:
                history = json.load(f)
            implicated = history.get("implicated", {})
            if not implicated.get("series"):
                problems.append("history.json has no implicated series")
            if implicated.get("metric") != incident.get("metric"):
                problems.append("history.json implicates the wrong metric")
        except ValueError:
            problems.append("history.json unparseable")
    dump_path = evidence.get("flight_dump")
    if not dump_path or not os.path.isfile(dump_path):
        problems.append(f"no flight dump ({dump_path!r})")
    return problems


def _phase(base: str, model: str, x, n_requests: int, rng):
    """Drive one phase; returns per-phase stats."""
    statuses = []
    degraded = 0
    hung = 0
    for _ in range(n_requests):
        n = int(rng.integers(1, 9))
        start = int(rng.integers(0, x.shape[0] - n))
        t0 = time.monotonic()
        status, payload = _post_predict(base, model, x[start:start + n])
        if status == 0:
            hung += 1
        if status == 200 and payload.get("degraded"):
            degraded += 1
        statuses.append(status)
        _ = time.monotonic() - t0
    ok = sum(1 for s in statuses if s == 200)
    return {
        "requests": n_requests,
        "ok": ok,
        "availability": ok / n_requests if n_requests else 0.0,
        "degraded": degraded,
        "hung": hung,
        "statuses": sorted(set(statuses)),
    }


def _concurrent_burst(base: str, model: str, x, n_requests: int, rng,
                      width: int = 4):
    """Drive one phase from ``width`` client threads at once, so the
    pipelined batcher genuinely holds batches in its in-flight window
    while the fault fires (the serial ``_phase`` loop rarely gets two
    batches in flight). Same stats shape as ``_phase``."""
    import threading

    jobs = [(int(rng.integers(1, 9)),
             int(rng.integers(0, x.shape[0] - 9)))
            for _ in range(n_requests)]
    results = []
    lock = threading.Lock()
    cursor = {"i": 0}

    def worker():
        while True:
            with lock:
                if cursor["i"] >= len(jobs):
                    return
                n, start = jobs[cursor["i"]]
                cursor["i"] += 1
            status, payload = _post_predict(base, model,
                                            x[start:start + n])
            with lock:
                results.append(
                    (status, bool(payload.get("degraded"))))

    threads = [threading.Thread(target=worker) for _ in range(width)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ok = sum(1 for s, _ in results if s == 200)
    return {
        "requests": n_requests,
        "ok": ok,
        "availability": ok / n_requests if n_requests else 0.0,
        "degraded": sum(1 for _, d in results if d),
        "hung": sum(1 for s, _ in results if s == 0),
        "statuses": sorted({s for s, _ in results}),
    }


def _tenant_burst(base: str, model: str, x, seconds: float,
                  greedy_width: int = 18, compliant_width: int = 4):
    """The overload phase's client fleet: a greedy batch-priority tenant
    flooding closed-loop from ``greedy_width`` threads (tiny quota → ~10x
    over it) alongside a compliant interactive tenant — per-tenant stats
    so the fairness contract is assertable from the wire."""
    import threading

    lock = threading.Lock()
    results = {"chaos_greedy": [], "chaos_compliant": []}
    seeds = iter(range(1000, 2000))
    stop_at = time.monotonic() + seconds

    def client(tenant: str, priority: str, seed: int):
        local_rng = np.random.default_rng(seed)
        while time.monotonic() < stop_at:
            n = int(local_rng.integers(4, 9))
            start = int(local_rng.integers(0, x.shape[0] - n))
            status, payload = _post_predict(
                base, model, x[start:start + n],
                tenant=tenant, priority=priority)
            with lock:
                results[tenant].append(
                    (status, bool(payload.get("shed")),
                     bool(payload.get("degraded"))))
            if status != 200:
                # bounded spin: a rejected closed-loop client hammering
                # at GIL speed would measure the client, not the server
                time.sleep(0.005)

    threads = [
        threading.Thread(target=client,
                         args=("chaos_greedy", "batch", next(seeds)),
                         daemon=True)
        for _ in range(greedy_width)
    ] + [
        threading.Thread(target=client,
                         args=("chaos_compliant", "interactive",
                               next(seeds)),
                         daemon=True)
        for _ in range(compliant_width)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(seconds + 60.0)

    def stats(tenant: str) -> dict:
        rs = results[tenant]
        ok = sum(1 for s, _shed, _d in rs if s == 200)
        return {
            "requests": len(rs),
            "ok": ok,
            "availability": ok / len(rs) if rs else 0.0,
            "shed": sum(1 for s, shed, _d in rs if shed and s != 200),
            "degraded": sum(1 for s, _shed, d in rs
                            if d and s == 200),
            "hung": sum(1 for s, _shed, _d in rs if s == 0),
            "statuses": sorted({s for s, _shed, _d in rs}),
        }

    return {"greedy": stats("chaos_greedy"),
            "compliant": stats("chaos_compliant")}


def main() -> int:
    if os.environ.get("SPARKML_CHAOS_PHASE") == "replica_drain_child":
        return replica_drain_child()
    if os.environ.get("SPARKML_CHAOS_PHASE") == "canary_rollback_child":
        return canary_rollback_child()
    if os.environ.get("SPARKML_CHAOS_PHASE") == "autoscale_flap_child":
        return autoscale_flap_child()
    n_requests = _env_int("SPARKML_CHAOS_REQUESTS", 24)
    n_features = _env_int("SPARKML_CHAOS_FEATURES", 16)
    k = _env_int("SPARKML_CHAOS_K", 4)
    min_availability = float(
        os.environ.get("SPARKML_CHAOS_MIN_AVAILABILITY", 0.5))

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        ServeEngine,
        fault_plane,
        start_serve_server,
    )

    rng = np.random.default_rng(13)
    x = rng.normal(size=(1024, n_features))
    model = PCA().setK(k).fit(x)

    registry = ModelRegistry()
    registry.register("chaos_pca", model, buckets=(16, 64))
    # worker budget 900 ms: far under the 2 s injected stall it must
    # catch, but WELL above the overload phase's worst case — a 150 ms
    # latency-faulted batch whose watchdog spans the depth-2 in-flight
    # window (~2 batch dispatches + a completion ≈ 0.35 s, plus GIL
    # noise). At 500 ms the overload phase read as a wedge storm and
    # the resulting WorkerCrashed failures opened the breaker — exactly
    # the "overload must never read as backend failure" confusion the
    # phase exists to rule out.
    engine = ServeEngine(
        registry, max_batch_rows=64, max_wait_ms=1.0,
        retries=2, backoff_ms=10,
        breaker_failures=3, breaker_cooldown_ms=400,
        worker_budget_ms=900, default_deadline_ms=10_000,
    )
    registry.warmup("chaos_pca")
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    plane = fault_plane()
    phases = {}
    incidents = {}
    incident_totals = {}
    breaker_open_at = None
    breaker_open_seconds = 0.0

    def breaker_state():
        snap = engine.breaker_snapshot().get("chaos_pca")
        return snap["state"] if snap else "closed"

    def _await_closed(budget: float = 30.0) -> float:
        """Drive probe traffic until the breaker closes (each fault
        class must start from a healthy state); returns how long it
        took."""
        t0 = time.monotonic()
        while (breaker_state() != "closed"
               and time.monotonic() < t0 + budget):
            time.sleep(0.1)
            n = int(rng.integers(1, 9))
            start = int(rng.integers(0, x.shape[0] - n))
            _post_predict(base, "chaos_pca", x[start:start + n])
        return time.monotonic() - t0

    def _known_ids(detector: str) -> set:
        doc = _get_json(base, "/debug/incidents")
        return {i.get("id") for i in _incident_entries(doc, detector)}

    def _check_incident_loop(detector: str, known_ids: set,
                             exactly_one: bool = True) -> dict:
        """The auto-incident contract for one fault class: NEW
        incident(s) from the expected detector, each with a complete
        evidence bundle on disk, each auto-resolved after recovery.

        ``exactly_one`` (the error-class phases) additionally asserts
        the dedup contract — a square-wave fault burst must open ONE
        incident, continued firing updating it. The overload phase
        passes ``exactly_one=False``: a queue oscillating around the
        shed controller's equilibrium can legitimately resolve and
        re-open past the cooldown — the contract there is that the
        loop detects and closes, not that 8 s of oscillation is one
        square wave."""
        new = _await_new_incidents(base, detector, known_ids)
        result = {"detector": detector, "new_incidents": len(new)}
        bad_count = (len(new) != 1) if exactly_one else (len(new) < 1)
        if bad_count:
            expected = "exactly 1" if exactly_one else ">= 1"
            result["problems"] = [
                f"expected {expected} new {detector} incident(s), "
                f"saw {len(new)}"
            ]
            return result
        result["incident_id"] = new[0].get("id")
        problems = []
        resolved_all = True
        for incident in new:
            problems.extend(_bundle_problems(incident))
            if not _await_resolved(base, incident["id"]):
                resolved_all = False
                problems.append(
                    f"{incident['id']} did not auto-resolve after "
                    "recovery")
        result["resolved"] = resolved_all
        if problems:
            result["problems"] = problems
        else:
            bench_common.log(
                f"chaos incident loop OK: {detector} opened "
                f"{', '.join(i['id'] for i in new)} (bundle "
                f"{(new[0].get('evidence') or {}).get('dir')}) "
                "and auto-resolved")
        return result

    def _warm(n: int) -> None:
        """Healthy traffic right before an error-class storm: the
        error-rate detector judges the error FRACTION over its short
        window with a min-traffic floor, and by the time one fault
        class's incident has resolved (its errors aged out of the
        window) the previous phase's OK requests have aged out too —
        without fresh denominator traffic, three burst errors read as
        3/3 of nothing and the floor keeps the detector silent."""
        for _ in range(n):
            rows = int(rng.integers(1, 9))
            start = int(rng.integers(0, x.shape[0] - rows))
            _post_predict(base, "chaos_pca", x[start:start + rows])

    try:
        bench_common.log("chaos baseline")
        phases["baseline"] = _phase(base, "chaos_pca", x, n_requests, rng)

        # -- the storm: each fault class in turn, each from a healthy
        # breaker (otherwise the first class's open breaker routes every
        # later phase around the device and the later faults never
        # fire), and each awaited through its auto-incident loop so the
        # next error-class phase starts from a resolved detector (the
        # dedup/cooldown contract is per (detector, series)).
        #
        # latency runs FIRST: the p99 detector judges the CUMULATIVE
        # latency sketch, so the spike must land on a pristine tail —
        # after a raise/stall phase the retry+backoff stragglers have
        # already dragged p99 up and a further +50 ms cannot clear the
        # detector's min_step/min_relative guards against paging twice
        # on one regression.
        # +120 ms per call: the p99 detector needs a >= 2x jump over the
        # cumulative tail, and on a noisy shared-CPU container the
        # baseline p99 can already sit near 60-80 ms — a +50 ms spike
        # then reads as within-noise and the incident contract flakes.
        bench_common.log("chaos latency spike (+120ms per call)")
        known = _known_ids("serve_p99_spike")
        plane.inject("chaos_pca", "latency", count=None, seconds=0.12)
        phases["latency"] = _phase(base, "chaos_pca", x,
                                   max(n_requests // 2, 8), rng)
        plane.clear()
        incidents["latency"] = _check_incident_loop("serve_p99_spike",
                                                    known)

        bench_common.log("chaos raise storm (100% backend errors)")
        _warm(max(n_requests // 2, 12))
        known = _known_ids("serve_error_rate")
        plane.inject("chaos_pca", "raise", count=None)
        phases["raise"] = _phase(base, "chaos_pca", x, n_requests, rng)
        if breaker_state() != "closed":
            breaker_open_at = time.monotonic()
        plane.clear()
        opened_for = _await_closed()
        if breaker_open_at is not None:
            breaker_open_seconds += opened_for
        incidents["raise"] = _check_incident_loop("serve_error_rate",
                                                  known)

        bench_common.log("chaos stall (transform wedges past the budget)")
        _warm(max(n_requests // 2, 12))
        known = _known_ids("serve_error_rate")
        plane.inject("chaos_pca", "stall", count=3, seconds=2.0)
        phases["stall"] = _phase(base, "chaos_pca", x,
                                 max(n_requests // 2, 8), rng)
        plane.clear()
        _await_closed()
        incidents["stall"] = _check_incident_loop("serve_error_rate",
                                                  known)

        bench_common.log("chaos nan corruption")
        _warm(max(n_requests // 2, 12))
        known = _known_ids("serve_error_rate")
        plane.inject("chaos_pca", "nan", count=3)
        phases["nan"] = _phase(base, "chaos_pca", x,
                               max(n_requests // 2, 8), rng)
        plane.clear()
        _await_closed()
        incidents["nan"] = _check_incident_loop("serve_error_rate",
                                                known)

        # -- overload: closed-loop 2x+ capacity from a greedy tenant
        # with a tiny quota, alongside a compliant interactive tenant.
        # A +120 ms latency fault plays the role of "the device is the
        # bottleneck" so the queue genuinely builds at drill scale. The
        # invariants: the compliant tenant keeps its availability, the
        # queue-depth detector opens (and resolves) an incident, and
        # the breaker NEVER opens — overload and slowness are not
        # backend failure (the PR 6 invariant, extended to shedding).
        bench_common.log("chaos overload (2x closed-loop, mixed tenants)")
        _warm(max(n_requests // 2, 12))
        known = _known_ids("serve_queue_depth")
        # 150 ms per batch: deep enough queueing (22 closed-loop
        # clients vs ~10-request batches) that the depth detector sees
        # a sustained spike BEFORE the controller's queue-wait EWMA
        # crosses its 200 ms target and shedding drains the backlog —
        # while staying FAR under the 900 ms worker budget even across
        # the depth-2 in-flight window (a 300 ms fault span read as a
        # wedge storm under load, and WorkerCrashed opened the breaker
        # this phase exists to keep closed).
        plane.inject("chaos_pca", "latency", count=None, seconds=0.15)
        burst = _tenant_burst(base, "chaos_pca", x, 8.0)
        phases["overload_greedy"] = burst["greedy"]
        phases["overload_compliant"] = burst["compliant"]
        overload_breaker_state = breaker_state()
        plane.clear()
        incidents["overload"] = _check_incident_loop(
            "serve_queue_depth", known, exactly_one=False)
        # drain the shed level before the pipelined phases (quiet
        # signals de-escalate after the hold)
        time.sleep(2.5)

        # -- the pipelined drill: the same fault classes with batches
        # genuinely IN FLIGHT (concurrent clients + the async window,
        # PIPELINE_DEPTH default 2). The breaker/retry/incident
        # machinery must behave identically, and a worker restart must
        # leave no stuck in-flight window behind.
        bench_common.log(
            f"chaos pipelined latency (+20 ms, depth="
            f"{engine.pipeline_depth}, concurrent clients)")
        _warm(max(n_requests // 2, 12))
        plane.inject("chaos_pca", "latency", count=None, seconds=0.02)
        phases["pipelined_latency"] = _concurrent_burst(
            base, "chaos_pca", x, max(n_requests // 2, 8), rng)
        plane.clear()

        bench_common.log(
            "chaos pipelined stall (wedge mid-window -> restart)")
        plane.inject("chaos_pca", "stall", count=1, seconds=2.0)
        phases["pipelined_stall"] = _concurrent_burst(
            base, "chaos_pca", x, max(n_requests // 2, 8), rng)
        plane.clear()
        # no stuck in-flight window after the restart: the queue drains
        # and a fresh request answers once the breaker re-admits traffic
        t0 = time.monotonic()
        while engine.queue_depth() > 0 and time.monotonic() < t0 + 10:
            time.sleep(0.05)
        pipeline_stuck_window = engine.queue_depth() > 0
        _await_closed()
        status, _payload = _post_predict(base, "chaos_pca", x[:4])
        pipeline_recovered = status == 200
        # Let the abandoned wedged worker clear its 2 s stall and exit
        # cleanly BEFORE the drill ends: a daemon thread still inside a
        # jax call at interpreter teardown aborts the whole process
        # ("terminate called without an active exception") after the
        # verdict has already been decided.
        time.sleep(2.5)

        # -- recovery: wait out the cooldown, let a probe close it -------
        bench_common.log("chaos recovery (faults cleared)")
        recovery_seconds = _await_closed()
        phases["recovery"] = _phase(base, "chaos_pca", x, n_requests, rng)
        incident_totals = _get_json(base, "/debug/incidents")

        # -- replica drain: fault ONE device's replica (2-device
        # subprocess — device count is fixed at jax init) and prove the
        # placement tier sheds onto the sibling without taking the tier
        # down, with its own incident loop.
        bench_common.log("chaos replica drain (2-device subprocess)")
        replica_drain = run_replica_drain_phase()

        # -- canary rollback: stream-fit a candidate, canary it on live
        # alias traffic, fault ONLY the candidate version, and prove the
        # rollout tier rolls the alias back (own subprocess — fresh
        # incident engine, nothing shared with this drill's detectors).
        bench_common.log("chaos canary rollback (train-while-serving)")
        canary_rollback = run_canary_rollback_phase()

        # -- autoscale flap: an oscillating load square-wave must not
        # flap the replica controller faster than its hysteresis hold
        # (4-device subprocess, own incident engine).
        bench_common.log("chaos autoscale flap (4-device subprocess)")
        autoscale_flap = run_autoscale_flap_phase()
    finally:
        plane.clear()
        server.shutdown()
        engine.shutdown()
        # Stop the background sampler BEFORE interpreter teardown: a
        # daemon sweep mid-jax-call (devmon memory_stats) at
        # finalization aborts the process after the verdict.
        from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod

        tsdb_mod.get_sampler().stop()

    fault_phases = ("raise", "stall", "nan", "latency")
    fault_requests = sum(phases[p]["requests"] for p in fault_phases)
    fault_ok = sum(phases[p]["ok"] for p in fault_phases)
    # The pipelined phases get their OWN gate (not folded into
    # availability_under_fault, whose committed history predates them):
    # the behavior-parity claim is that faults with batches in flight
    # are no worse than the serial phases.
    availability_pipelined = min(
        phases[p]["availability"]
        for p in ("pipelined_latency", "pipelined_stall"))
    hung_total = sum(p["hung"] for p in phases.values())
    availability_under_fault = (fault_ok / fault_requests
                                if fault_requests else 0.0)
    record = {
        "bench": "chaos_drill",
        "availability_baseline": phases["baseline"]["availability"],
        "availability_under_fault": availability_under_fault,
        "availability_recovery": phases["recovery"]["availability"],
        "degraded_served": sum(p["degraded"] for p in phases.values()),
        "breaker_open_seconds": breaker_open_seconds,
        "recovery_seconds": recovery_seconds,
        "final_breaker_state": breaker_state(),
        "pipeline_depth": engine.pipeline_depth,
        "pipeline_stuck_window": pipeline_stuck_window,
        "pipeline_recovered": pipeline_recovered,
        "availability_pipelined": availability_pipelined,
        "availability_overload_compliant":
            phases["overload_compliant"]["availability"],
        "availability_overload_greedy":
            phases["overload_greedy"]["availability"],
        "overload_shed": phases["overload_greedy"]["shed"],
        "overload_breaker_state": overload_breaker_state,
        "incidents_opened": incident_totals.get("opened_total", 0),
        "incidents_resolved": incident_totals.get("resolved_total", 0),
        "incidents": incidents,
        "replica_drain": replica_drain,
        "availability_replica_drain": replica_drain.get(
            "availability", 0.0),
        "canary_rollback": canary_rollback,
        "availability_canary_incumbent": canary_rollback.get(
            "non_canary_availability", 0.0),
        "autoscale_flap": autoscale_flap,
        "availability_autoscale_flap": autoscale_flap.get(
            "availability", 0.0),
        "phases": {name: {k: v for k, v in stats.items()
                          if k != "statuses"}
                   for name, stats in phases.items()},
    }
    bench_common.emit_record(record)
    if hung_total:
        bench_common.log(f"chaos FAIL: {hung_total} request(s) hung")
        return 1
    if availability_under_fault < min_availability:
        bench_common.log(
            f"chaos FAIL: availability under fault "
            f"{availability_under_fault:.2f} < {min_availability}")
        return 1
    if record["final_breaker_state"] != "closed":
        bench_common.log("chaos FAIL: breaker did not close after recovery")
        return 1
    overload_min = float(
        os.environ.get("SPARKML_CHAOS_OVERLOAD_AVAILABILITY", 0.9))
    if record["availability_overload_compliant"] < overload_min:
        bench_common.log(
            f"chaos FAIL: compliant-tenant availability under overload "
            f"{record['availability_overload_compliant']:.2f} < "
            f"{overload_min}")
        return 1
    if record["overload_breaker_state"] != "closed":
        bench_common.log(
            "chaos FAIL: breaker opened during pure overload — "
            "shedding/slowness must never read as backend failure")
        return 1
    if availability_pipelined < min_availability:
        bench_common.log(
            f"chaos FAIL: pipelined-phase availability "
            f"{availability_pipelined:.2f} < {min_availability}")
        return 1
    if record["pipeline_stuck_window"]:
        bench_common.log(
            "chaos FAIL: in-flight window stuck after the pipelined "
            "worker restart (queue never drained)")
        return 1
    if not record["pipeline_recovered"]:
        bench_common.log(
            "chaos FAIL: no 200 answer after the pipelined stall "
            "restart + breaker recovery")
        return 1
    incident_failures = {name: check["problems"]
                         for name, check in incidents.items()
                         if check.get("problems")}
    if incident_failures:
        bench_common.log(
            f"chaos FAIL: incident loop broke for "
            f"{sorted(incident_failures)}: {incident_failures}")
        return 1
    replica_min = float(
        os.environ.get("SPARKML_CHAOS_REPLICA_AVAILABILITY", 0.99))
    if replica_drain.get("availability", 0.0) < replica_min:
        bench_common.log(
            f"chaos FAIL: replica-drain availability "
            f"{replica_drain.get('availability', 0.0):.3f} < "
            f"{replica_min} — the surviving replica did not absorb "
            "the faulted one")
        return 1
    if replica_drain.get("problems"):
        bench_common.log(
            f"chaos FAIL: replica-drain contract broke: "
            f"{replica_drain['problems']}")
        return 1
    if canary_rollback.get("non_canary_availability", 0.0) < 0.999:
        bench_common.log(
            f"chaos FAIL: non-canary availability "
            f"{canary_rollback.get('non_canary_availability', 0.0):.3f} "
            "< 1.0 — a candidate-targeted fault leaked onto the "
            "incumbent's traffic")
        return 1
    if canary_rollback.get("problems"):
        bench_common.log(
            f"chaos FAIL: canary-rollback contract broke: "
            f"{canary_rollback['problems']}")
        return 1
    if autoscale_flap.get("problems"):
        bench_common.log(
            f"chaos FAIL: autoscale-flap contract broke: "
            f"{autoscale_flap['problems']}")
        return 1
    bench_common.log("chaos drill PASS")
    # final settle: any worker abandoned mid-jax-call must leave the
    # call before interpreter teardown, or the process aborts AFTER the
    # verdict ("terminate called without an active exception")
    time.sleep(1.5)
    return 0


if __name__ == "__main__":
    sys.exit(main())
