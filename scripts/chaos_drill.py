#!/usr/bin/env python
"""Chaos drill: run the fault matrix against a live serve server.

Stands up the real stack — fitted PCA model, registry, engine with
retries + breaker + degraded CPU fallback, stdlib HTTP server — then
attacks it through the fault-injection plane (``serve.faults``), one
fault class at a time, measuring what a client on the wire experiences:

* **baseline**   — no faults: availability must be 1.0;
* **raise**      — 100% backend errors: the breaker opens, traffic
  degrades to the CPU fallback, availability stays high;
* **stall**      — a transform wedges past the worker budget: the
  watchdog fails it fast (``WorkerCrashed`` → 503), the worker
  restarts, traffic continues;
* **nan**        — corrupted outputs: the NaN guard converts poison
  into retryable errors;
* **latency**    — +spike on every call: answers stay correct, the SLO
  latency burn shows it;
* **recovery**   — faults cleared: a half-open probe closes the
  breaker and availability returns to 1.0.

Every request gets exactly one terminal outcome (the drill exits 1 if
any hangs past its client timeout, or if availability under fault drops
below ``SPARKML_CHAOS_MIN_AVAILABILITY``, default 0.5), and the drill
emits ONE ``bench_common.emit_record`` line the perf sentinel can judge
against committed history:

* ``availability_baseline`` / ``availability_under_fault`` /
  ``availability_recovery`` — fraction of requests answered 200
  (degraded answers count: the service answered);
* ``degraded_served``       — how many answers came from the CPU
  fallback;
* ``breaker_open_seconds``  — how long the breaker was open during the
  drill (lower = faster recovery);
* ``recovery_seconds``      — fault cleared → breaker closed again.

Knobs (env): SPARKML_CHAOS_REQUESTS (per phase, default 24),
SPARKML_CHAOS_FEATURES (16), SPARKML_CHAOS_K (4).
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.error
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench_common  # noqa: E402 (scripts/ on path when run directly)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _post_predict(base: str, model: str, rows, timeout: float = 15.0):
    """One HTTP predict; returns (status, payload_dict). Never raises —
    a drill request that cannot be categorized is itself a finding."""
    body = json.dumps({"model": model, "rows": rows.tolist()}).encode()
    req = urllib.request.Request(
        f"{base}/predict", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        try:
            payload = json.loads(exc.read())
        except ValueError:
            payload = {}
        return exc.code, payload
    except Exception as exc:  # noqa: BLE001 - hang/reset IS the result
        return 0, {"error": f"{type(exc).__name__}: {exc}"}


def _phase(base: str, model: str, x, n_requests: int, rng):
    """Drive one phase; returns per-phase stats."""
    statuses = []
    degraded = 0
    hung = 0
    for _ in range(n_requests):
        n = int(rng.integers(1, 9))
        start = int(rng.integers(0, x.shape[0] - n))
        t0 = time.monotonic()
        status, payload = _post_predict(base, model, x[start:start + n])
        if status == 0:
            hung += 1
        if status == 200 and payload.get("degraded"):
            degraded += 1
        statuses.append(status)
        _ = time.monotonic() - t0
    ok = sum(1 for s in statuses if s == 200)
    return {
        "requests": n_requests,
        "ok": ok,
        "availability": ok / n_requests if n_requests else 0.0,
        "degraded": degraded,
        "hung": hung,
        "statuses": sorted(set(statuses)),
    }


def main() -> int:
    n_requests = _env_int("SPARKML_CHAOS_REQUESTS", 24)
    n_features = _env_int("SPARKML_CHAOS_FEATURES", 16)
    k = _env_int("SPARKML_CHAOS_K", 4)
    min_availability = float(
        os.environ.get("SPARKML_CHAOS_MIN_AVAILABILITY", 0.5))

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import (
        ModelRegistry,
        ServeEngine,
        fault_plane,
        start_serve_server,
    )

    rng = np.random.default_rng(13)
    x = rng.normal(size=(1024, n_features))
    model = PCA().setK(k).fit(x)

    registry = ModelRegistry()
    registry.register("chaos_pca", model, buckets=(16, 64))
    engine = ServeEngine(
        registry, max_batch_rows=64, max_wait_ms=1.0,
        retries=2, backoff_ms=10,
        breaker_failures=3, breaker_cooldown_ms=400,
        worker_budget_ms=500, default_deadline_ms=10_000,
    )
    registry.warmup("chaos_pca")
    server = start_serve_server(engine)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    plane = fault_plane()
    phases = {}
    breaker_open_at = None
    breaker_open_seconds = 0.0

    def breaker_state():
        snap = engine.breaker_snapshot().get("chaos_pca")
        return snap["state"] if snap else "closed"

    def _await_closed(budget: float = 30.0) -> float:
        """Drive probe traffic until the breaker closes (each fault
        class must start from a healthy state); returns how long it
        took."""
        t0 = time.monotonic()
        while (breaker_state() != "closed"
               and time.monotonic() < t0 + budget):
            time.sleep(0.1)
            n = int(rng.integers(1, 9))
            start = int(rng.integers(0, x.shape[0] - n))
            _post_predict(base, "chaos_pca", x[start:start + n])
        return time.monotonic() - t0

    try:
        bench_common.log("chaos baseline")
        phases["baseline"] = _phase(base, "chaos_pca", x, n_requests, rng)

        # -- the storm: each fault class in turn, each from a healthy
        # breaker (otherwise the first class's open breaker routes every
        # later phase around the device and the later faults never fire)
        bench_common.log("chaos raise storm (100% backend errors)")
        plane.inject("chaos_pca", "raise", count=None)
        phases["raise"] = _phase(base, "chaos_pca", x, n_requests, rng)
        if breaker_state() != "closed":
            breaker_open_at = time.monotonic()
        plane.clear()
        opened_for = _await_closed()
        if breaker_open_at is not None:
            breaker_open_seconds += opened_for

        bench_common.log("chaos stall (transform wedges past the budget)")
        plane.inject("chaos_pca", "stall", count=1, seconds=2.0)
        phases["stall"] = _phase(base, "chaos_pca", x, max(n_requests // 4, 4),
                                 rng)
        plane.clear()
        _await_closed()

        bench_common.log("chaos nan corruption")
        plane.inject("chaos_pca", "nan", count=2)
        phases["nan"] = _phase(base, "chaos_pca", x, max(n_requests // 4, 4),
                               rng)
        plane.clear()
        _await_closed()

        bench_common.log("chaos latency spike (+50ms per call)")
        plane.inject("chaos_pca", "latency", count=None, seconds=0.05)
        phases["latency"] = _phase(base, "chaos_pca", x,
                                   max(n_requests // 4, 4), rng)
        plane.clear()

        # -- recovery: wait out the cooldown, let a probe close it -------
        bench_common.log("chaos recovery (faults cleared)")
        recovery_seconds = _await_closed()
        phases["recovery"] = _phase(base, "chaos_pca", x, n_requests, rng)
    finally:
        plane.clear()
        server.shutdown()
        engine.shutdown()

    fault_phases = ("raise", "stall", "nan", "latency")
    fault_requests = sum(phases[p]["requests"] for p in fault_phases)
    fault_ok = sum(phases[p]["ok"] for p in fault_phases)
    hung_total = sum(p["hung"] for p in phases.values())
    availability_under_fault = (fault_ok / fault_requests
                                if fault_requests else 0.0)
    record = {
        "bench": "chaos_drill",
        "availability_baseline": phases["baseline"]["availability"],
        "availability_under_fault": availability_under_fault,
        "availability_recovery": phases["recovery"]["availability"],
        "degraded_served": sum(p["degraded"] for p in phases.values()),
        "breaker_open_seconds": breaker_open_seconds,
        "recovery_seconds": recovery_seconds,
        "final_breaker_state": breaker_state(),
        "phases": {name: {k: v for k, v in stats.items()
                          if k != "statuses"}
                   for name, stats in phases.items()},
    }
    bench_common.emit_record(record)
    if hung_total:
        bench_common.log(f"chaos FAIL: {hung_total} request(s) hung")
        return 1
    if availability_under_fault < min_availability:
        bench_common.log(
            f"chaos FAIL: availability under fault "
            f"{availability_under_fault:.2f} < {min_availability}")
        return 1
    if record["final_breaker_state"] != "closed":
        bench_common.log("chaos FAIL: breaker did not close after recovery")
        return 1
    bench_common.log("chaos drill PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
