"""Shared helpers for the bench family: probe/log/record plumbing AND the
single JSON-emission path.

One copy of what bench_r04_once.py, bench_r04_wave2.py, and
bench_r04_wave3.py previously each carried: the probe contract (exit 2 →
wrapper retries) and the "capture bench.main() stdout → annotate last JSON
line → write record" sequence. ``emit_record`` is the ONE way every bench
(bench.py, bench_scale.py, bench_gram_sweep.py, the wave scripts) emits its
final JSON line — it stamps the record and embeds a metrics-registry
snapshot, so per-fit collective/phase accounting rides along with every
bench number instead of each script hand-rolling ``json.dumps``.
"""

from __future__ import annotations

import contextlib
import datetime
import io
import json
import os
import sys
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "records", "r04")
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def stamp() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def log(msg: str) -> None:
    os.makedirs(OUT, exist_ok=True)
    with open(os.path.join(OUT, "status.log"), "a") as f:
        f.write(f"{msg}: {stamp()}\n")


def force_device_count_flags(n_devices: int, env: dict = None) -> str:
    """The ``XLA_FLAGS`` value a subprocess child needs to see
    ``n_devices`` forced host devices, preserving every other flag the
    parent environment carries (device count is fixed at jax init, so
    multi-device-count benches spawn one child per count). Shared by
    bench_serve's multidevice scenario, load_harness's device-scaling
    phase, and chaos_drill's replica_drain phase — one copy of the
    flag-splicing logic."""
    source = os.environ if env is None else env
    kept = [f for f in source.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append(f"--xla_force_host_platform_device_count={n_devices}")
    return " ".join(kept)


def prefixed_result(stdout: str, prefix: str):
    """The machine-readable child-result line a subprocess leg printed
    (``PREFIX {json}``), parsed — or None when the child never emitted
    one (the caller reports rc/stderr)."""
    line = next((ln for ln in (stdout or "").splitlines()
                 if ln.startswith(prefix)), None)
    if line is None:
        return None
    return json.loads(line[len(prefix):])


_REQUIRE_PLATFORM_ENV = "SPARKML_BENCH_REQUIRE_PLATFORM"


def backend_provenance() -> dict:
    """The RESOLVED jax backend (not the requested one): platform,
    device kind, device count. {} when jax is unavailable — provenance
    must never fail a bench. Callers on the emit path have already
    initialized the backend, so this never triggers a fresh init cost."""
    try:
        import jax

        devices = jax.devices()
        return {
            "platform": devices[0].platform,
            "device_kind": getattr(devices[0], "device_kind", None),
            "device_count": len(devices),
        }
    except Exception:  # noqa: BLE001 - provenance must never fail a bench
        return {}


def required_platform() -> str | None:
    """The platform this bench run REQUIRES (``SPARKML_BENCH_REQUIRE_
    PLATFORM=tpu``), or None when any resolved backend is acceptable."""
    value = os.environ.get(_REQUIRE_PLATFORM_ENV, "").strip().lower()
    return value or None


def enforce_required_platform(provenance: dict | None = None) -> dict:
    """Refuse to continue when the resolved backend is not the required
    one — a record measured on a silent CPU fallback is worse than no
    record (the r04 lesson). Exit code 3 distinguishes the refusal from
    a probe retry (2). Returns the provenance when the check passes."""
    want = required_platform()
    prov = provenance if provenance is not None else backend_provenance()
    if want is None:
        return prov
    got = (prov.get("platform") or "").lower()
    if got != want:
        log(f"backend mismatch: required {want}, resolved {got or 'none'}")
        flight_dump("bench_backend_mismatch", required=want,
                    resolved=got or None)
        print(json.dumps({
            "error": "backend_mismatch",
            "required_platform": want,
            "resolved_platform": got or None,
        }), flush=True)
        raise SystemExit(3)
    return prov


def metrics_snapshot() -> dict:
    """The process metrics registry as a JSON-safe dict ({} when the
    package (or its telemetry) is unavailable — emission never fails)."""
    try:
        from spark_rapids_ml_tpu.obs import get_registry

        return get_registry().snapshot()
    except Exception:  # noqa: BLE001 - emission must never fail
        return {}


def emit_record(record: dict, *, stream=None, include_metrics: bool = True,
                flush: bool = True) -> dict:
    """Emit one bench record as a single JSON line (the LAST stdout line
    contract run_bench_to_record parses). Stamps ``emitted_utc`` and embeds
    the metrics-registry snapshot under ``"metrics"``. Returns the emitted
    dict. ``stream=None`` prints to stdout; pass an open file to append to
    a record file instead."""
    rec = dict(record)
    rec.setdefault("emitted_utc", stamp())
    if "backend" not in rec:
        # every record names the backend it was measured on — the
        # perf sentinel compares records only within one backend and
        # flags cross-backend drift as backend_mismatch, not regression
        prov = backend_provenance()
        if prov:
            rec["backend"] = prov
        want = required_platform()
        if want is not None:
            rec["required_platform"] = want
            enforce_required_platform(prov)
    if include_metrics and "metrics" not in rec:
        snap = metrics_snapshot()
        if snap:
            rec["metrics"] = snap
    line = json.dumps(rec)
    if stream is None:
        print(line, flush=flush)
    else:
        stream.write(line + "\n")
        if flush:
            stream.flush()
    return rec


def flight_dump(reason: str, **extra) -> str | None:
    """Flight-recorder dump, guarded: a wedge produces a diagnostic
    artifact (thread stacks, spans, metrics, cached health) in
    ``SPARK_RAPIDS_ML_TPU_DUMP_DIR``, never a bench failure."""
    try:
        from spark_rapids_ml_tpu.obs import flight

        return flight.dump(reason, extra=extra or None)
    except Exception:  # noqa: BLE001 - dumps must never break a bench
        return None


def probe(tag: str):
    """Claim the chip; return the device or None (caller exits 2 so the
    wrapper loop retries). Forces the TPU backend — a silent CPU
    fallback would burn the window measuring nothing. A failed probe
    leaves a flight-recorder dump, not just a status-log line."""
    os.environ.setdefault("JAX_PLATFORMS", "tpu")
    log(f"{tag} probe start")
    try:
        import jax

        device = jax.devices()[0]
    except Exception as exc:  # noqa: BLE001
        log(f"{tag} probe FAILED ({type(exc).__name__})")
        flight_dump("bench_probe_failed", tag=tag,
                    error=f"{type(exc).__name__}: {exc}")
        return None
    if device.platform == "cpu":
        log(f"{tag} probe FAILED (cpu backend)")
        flight_dump("bench_probe_cpu_fallback", tag=tag)
        return None
    want = required_platform()
    if want is not None and device.platform.lower() != want:
        log(f"{tag} probe FAILED (platform {device.platform} != "
            f"required {want})")
        flight_dump("bench_backend_mismatch", tag=tag, required=want,
                    resolved=device.platform)
        return None
    log(f"{tag} probe ok")
    return device


def is_unavailable(exc: BaseException) -> bool:
    """Chip-claim-lost errors (XLA UNAVAILABLE) — the caller should
    abort and let the wrapper retry the whole window, NOT record the
    failure as a per-step result."""
    return "UNAVAILABLE" in f"{type(exc).__name__}: {exc}"


def write_error(name: str, exc: BaseException) -> None:
    with open(os.path.join(OUT, f"{name}.err"), "w") as f:
        f.write(f"{type(exc).__name__}: {exc}\n")
        f.write(traceback.format_exc())


def run_bench_to_record(record_name: str, env: dict, annotate: dict,
                        tag: str) -> bool:
    """Run bench.main() under env overrides, annotate the final JSON
    line, write records/r04/<record_name>. Returns success; raises
    nothing (errors land in <record_name>.err). Chip-level UNAVAILABLE
    re-raises so the caller can abort the window."""
    import bench

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    except Exception as exc:  # noqa: BLE001
        if is_unavailable(exc):
            raise
        write_error(record_name.removesuffix(".json"), exc)
        log(f"{tag} FAILED")
        return False
    finally:
        for k, val in saved.items():
            if val is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = val
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    try:
        rec = json.loads(lines[-1])
        rec.update(annotate)
        rec["recorded_utc"] = stamp()
        lines[-1] = json.dumps(rec)
    except Exception:  # noqa: BLE001 - keep raw text on parse issues
        pass
    with open(os.path.join(OUT, record_name), "w") as f:
        f.write("\n".join(lines) + "\n")
    log(f"{tag} ok")
    return True
