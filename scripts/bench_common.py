"""Shared helpers for the round-4 chip bench orchestrators.

One copy of the probe/log/record plumbing that bench_r04_once.py,
bench_r04_wave2.py, and bench_r04_wave3.py previously each carried:
keeping the probe contract (exit 2 → wrapper retries) and the
"capture bench.main() stdout → annotate last JSON line → write record"
sequence in one place means a fix lands everywhere at once.
"""

from __future__ import annotations

import contextlib
import datetime
import io
import json
import os
import sys
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "records", "r04")
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def stamp() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def log(msg: str) -> None:
    os.makedirs(OUT, exist_ok=True)
    with open(os.path.join(OUT, "status.log"), "a") as f:
        f.write(f"{msg}: {stamp()}\n")


def probe(tag: str):
    """Claim the chip; return the device or None (caller exits 2 so the
    wrapper loop retries). Forces the TPU backend — a silent CPU
    fallback would burn the window measuring nothing."""
    os.environ.setdefault("JAX_PLATFORMS", "tpu")
    log(f"{tag} probe start")
    try:
        import jax

        device = jax.devices()[0]
    except Exception as exc:  # noqa: BLE001
        log(f"{tag} probe FAILED ({type(exc).__name__})")
        return None
    if device.platform == "cpu":
        log(f"{tag} probe FAILED (cpu backend)")
        return None
    log(f"{tag} probe ok")
    return device


def is_unavailable(exc: BaseException) -> bool:
    """Chip-claim-lost errors (XLA UNAVAILABLE) — the caller should
    abort and let the wrapper retry the whole window, NOT record the
    failure as a per-step result."""
    return "UNAVAILABLE" in f"{type(exc).__name__}: {exc}"


def write_error(name: str, exc: BaseException) -> None:
    with open(os.path.join(OUT, f"{name}.err"), "w") as f:
        f.write(f"{type(exc).__name__}: {exc}\n")
        f.write(traceback.format_exc())


def run_bench_to_record(record_name: str, env: dict, annotate: dict,
                        tag: str) -> bool:
    """Run bench.main() under env overrides, annotate the final JSON
    line, write records/r04/<record_name>. Returns success; raises
    nothing (errors land in <record_name>.err). Chip-level UNAVAILABLE
    re-raises so the caller can abort the window."""
    import bench

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    except Exception as exc:  # noqa: BLE001
        if is_unavailable(exc):
            raise
        write_error(record_name.removesuffix(".json"), exc)
        log(f"{tag} FAILED")
        return False
    finally:
        for k, val in saved.items():
            if val is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = val
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    try:
        rec = json.loads(lines[-1])
        rec.update(annotate)
        rec["recorded_utc"] = stamp()
        lines[-1] = json.dumps(rec)
    except Exception:  # noqa: BLE001 - keep raw text on parse issues
        pass
    with open(os.path.join(OUT, record_name), "w") as f:
        f.write("\n".join(lines) + "\n")
    log(f"{tag} ok")
    return True
