"""Quadratic-algorithm scale demonstration: DBSCAN + UMAP at 200k×64.

VERDICT r3 task #5 (carried from r2 #6): prove the tiled kernels handle
200k rows on one chip without OOM — the dense n×n formulation would need
n²·4B = 160 GB of HBM at this size; the tiled sweeps keep a block×n panel
(block 4096 → 3.3 GB) plus O(n) state resident. Prints one JSON line per
model: rows, wall-clock, peak device bytes (from PJRT memory_stats when
the backend exposes them), and the block envelope the peak must stay
inside. Asserts no-OOM by construction (completing is the proof) and,
when memory stats exist, that peak stays under the envelope.

On a CPU fallback the row count and epoch/sweep budgets shrink (the
point is the chip run; CPU only proves the code path end-to-end).

Run via a patient context (scripts/archive/bench_r04.sh) — never under a killable
timeout against the chip tunnel.
"""

from __future__ import annotations

import json  # noqa: F401 - kept for ad-hoc debugging
import os
import time

from bench_common import emit_record

import numpy as np

# the ONE watermark reader all benches share (obs/memory.py); the ad-hoc
# device.memory_stats() parsing that used to live here is retired
from spark_rapids_ml_tpu.obs.memory import peak_bytes_in_use as _peak_bytes


def main() -> None:
    import jax

    from spark_rapids_ml_tpu.utils.platform import force_cpu_if_requested

    force_cpu_if_requested()

    device = jax.devices()[0]
    platform = device.platform
    on_chip = platform not in ("cpu",)

    rows = int(os.environ.get("BSCALE_ROWS", 200_000 if on_chip else 40_000))
    cols = int(os.environ.get("BSCALE_COLS", 64))
    block = int(os.environ.get("BSCALE_BLOCK", 4096))
    umap_epochs = int(os.environ.get("BSCALE_UMAP_EPOCHS",
                                     50 if on_chip else 5))

    # well-separated blobs: DBSCAN's label-propagation sweep count stays
    # bounded by cluster diameter, and UMAP has real structure to embed
    rng = np.random.default_rng(0)
    n_blobs = 16
    centers = rng.normal(scale=12.0, size=(n_blobs, cols))
    assign = rng.integers(0, n_blobs, size=rows)
    x = centers[assign] + rng.normal(size=(rows, cols))

    # panel envelope: one (block, rows) f32 panel + x + O(rows) state,
    # with 4x headroom for XLA temporaries/donation copies
    envelope_bytes = 4 * (block * rows * 4 + x.nbytes + 64 * rows)

    from spark_rapids_ml_tpu.models.dbscan import DBSCAN
    from spark_rapids_ml_tpu.models.umap import UMAP

    records = []

    # eps: in 64 dims intra-blob pairwise distances concentrate at
    # √(2·64) ≈ 11.3 ± ~1 (σ=1 blobs), inter-blob centers ~136 apart —
    # eps=13 densely connects blobs and never bridges them
    t0 = time.perf_counter()
    db = DBSCAN().setEps(13.0).setMinPts(5).setBlockRows(block).fit(x)
    db_seconds = time.perf_counter() - t0
    n_clusters = int(db.n_clusters_)
    peak = _peak_bytes(device)
    rec = {
        "metric": f"DBSCAN.fit seconds ({rows}x{cols}, tiled block={block})",
        "value": round(db_seconds, 2),
        "unit": "seconds",
        "rows": rows,
        "platform": platform,
        "device_kind": str(getattr(device, "device_kind", platform)),
        "n_clusters": n_clusters,
        "rows_per_sec": round(rows / db_seconds, 1),
        "peak_device_bytes": peak,
        "envelope_bytes": envelope_bytes,
        "dense_equivalent_bytes": rows * rows * 4,
        "fit_timings": db.fit_timings_,
    }
    if peak is not None:
        assert peak < envelope_bytes, (
            f"peak {peak} exceeds block envelope {envelope_bytes}"
        )
        rec["within_envelope"] = True
    # widely-separated blobs: (nearly) every blob must resolve
    assert n_clusters >= n_blobs // 2, f"degenerate clustering: {n_clusters}"
    records.append(rec)
    emit_record(rec)

    t0 = time.perf_counter()
    um = (
        UMAP().setNNeighbors(15).setNEpochs(umap_epochs)
        .setBlockRows(block).fit(x)
    )
    um_seconds = time.perf_counter() - t0
    peak = _peak_bytes(device)
    emb = np.asarray(um.embedding_)
    assert np.isfinite(emb).all()
    # blob structure must survive the embedding: average inter-centroid
    # distance well above average intra-blob spread
    cent = np.stack([emb[assign == b].mean(axis=0) for b in range(n_blobs)])
    intra = float(np.mean([
        np.linalg.norm(emb[assign == b] - cent[b], axis=1).mean()
        for b in range(n_blobs)
    ]))
    inter = float(np.linalg.norm(
        cent[:, None, :] - cent[None, :, :], axis=-1
    )[np.triu_indices(n_blobs, 1)].mean())
    rec = {
        "metric": f"UMAP.fit seconds ({rows}x{cols}, tiled block={block}, "
                  f"epochs={umap_epochs})",
        "value": round(um_seconds, 2),
        "unit": "seconds",
        "rows": rows,
        "platform": platform,
        "device_kind": str(getattr(device, "device_kind", platform)),
        "rows_per_sec": round(rows / um_seconds, 1),
        "peak_device_bytes": peak,
        "envelope_bytes": envelope_bytes,
        "dense_equivalent_bytes": rows * rows * 4,
        "separation_ratio": round(inter / max(intra, 1e-9), 2),
        "fit_timings": um.fit_timings_,
    }
    if peak is not None:
        assert peak < envelope_bytes, (
            f"peak {peak} exceeds block envelope {envelope_bytes}"
        )
        rec["within_envelope"] = True
    # structure floor: blob centroids must already be pulling apart (the
    # recorded separation_ratio carries the full-budget evidence; the
    # reduced-epoch CPU smoke only proves direction)
    assert inter > 1.15 * intra, (
        f"blob structure lost: inter {inter:.2f} vs intra {intra:.2f}"
    )
    records.append(rec)
    emit_record(rec)


if __name__ == "__main__":
    main()
