"""UMAP scale bisect: ONLY run if wave-3's 200k retry reproduces the
UNAVAILABLE (VERDICT r4 #2 — a repeat failure means a real fault in the
blocked repulsion/kNN path, ``models/umap.py::_fit_blocked`` /
``ops/umap_kernel.py``, not transient claim collateral).

Runs the tiled fit at increasing row counts on the live chip, recording
each stage so the failing scale (and the last good one) are committed
even when the failing program kills the claim. One process, one claim;
exit 2 when no chip.
"""

from __future__ import annotations

import json
import os
import sys
import time

from bench_common import REPO, is_unavailable, log, probe, stamp

OUT5 = os.path.join(REPO, "records", "r05")


def main() -> int:
    device = probe("umap_bisect")
    if device is None:
        return 2

    import numpy as np

    from spark_rapids_ml_tpu.models.umap import UMAP

    os.makedirs(OUT5, exist_ok=True)
    path = os.path.join(OUT5, "umap_bisect.json")
    cols, epochs = 64, 20
    rng = np.random.default_rng(0)
    for rows in (50_000, 100_000, 150_000, 200_000):
        x = rng.normal(size=(rows, cols)).astype(np.float32)
        # two gaussian blobs so the embedding has structure to resolve
        x[rows // 2:] += 4.0
        rec = {"rows": rows, "cols": cols, "epochs": epochs,
               "recorded_utc": stamp()}
        try:
            t0 = time.perf_counter()
            um = (UMAP().setNNeighbors(15).setNEpochs(epochs)
                  .setInputCol("features").fit(x))
            emb = np.asarray(um.embedding_)
            rec["seconds"] = round(time.perf_counter() - t0, 2)
            rec["ok"] = bool(np.isfinite(emb).all())
            log(f"umap_bisect {rows} ok ({rec['seconds']}s)")
        except Exception as exc:  # noqa: BLE001
            rec["ok"] = False
            rec["error"] = f"{type(exc).__name__}: {exc}"[:500]
            log(f"umap_bisect {rows} FAILED ({type(exc).__name__})")
            with open(path, "a") as f:
                f.write(json.dumps(rec) + "\n")
            # UNAVAILABLE kills the claim — record and stop; the failing
            # scale is the diagnostic payload
            return 2 if is_unavailable(exc) else 1
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    log("umap_bisect ALL scales ok (fault not reproduced)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
