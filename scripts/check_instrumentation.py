#!/usr/bin/env python
"""Static check: every driver AND serving entry point is instrumented.

Three rule families:

1. over ``spark_rapids_ml_tpu/parallel/distributed_*.py``: every
   module-level public entry point (a ``distributed_*`` function that is
   not a ``*_kernel``) carries the ``@fit_instrumentation(...)`` decorator
   from ``spark_rapids_ml_tpu.obs``;
2. same files: no jitted entry point uses raw ``jax.jit`` — every jit
   decoration (and every ``jax.jit(...)`` call) must go through
   ``obs.tracked_jit`` / ``track_compiles``, so compile time, recompiles,
   and HLO cost analysis are observable for every driver program;
3. over ``spark_rapids_ml_tpu/models/*.py`` and
   ``spark_rapids_ml_tpu/spark/*.py``: every class-level serving entry
   point — a method named ``transform``/``predict``/``predict_proba``
   (plus ``_transform``, the pyspark-convention hook the base class's
   public ``transform`` delegates to, in ``spark/``) — carries the
   ``@observed_transform`` decorator from ``obs.serving``, so no
   transform/predict path ships as a telemetry black hole;
4. over ``spark_rapids_ml_tpu/serve/*.py`` (the serving engine): no raw
   ``jax.jit`` (same rule as the drivers), and no *instrumentation
   bypass* — the engine must drive models through their public,
   ``@observed_transform``-decorated entry points, so calls to a
   ``._transform(...)`` hook or directly into a ``*_kernel`` function
   are rejected: an engine batch that skipped the decorator would be
   invisible to the ``TransformReport``/numerics-sentinel layer;
5. same files: every queue/thread handoff goes through the
   ``obs.tracectx`` capture/activate helpers — raw
   ``threading.Thread(...)`` construction is rejected (use
   ``tracectx.traced_thread``, which snapshots contextvars), a
   ``.submit(...)`` enqueue without a ``trace_ctx=`` keyword is rejected
   (the queue must carry the request's identity across), and a response
   future resolution (``.set_result(...)`` / ``.set_error(...)``) inside
   a function that never ``activate(...)``-restores a context is
   rejected — a handoff that drops the ``TraceContext`` severs the
   request's trace at that seam;
6. same files: no silent exception swallows — a bare ``except:`` is
   rejected outright, and an ``except Exception``/``except
   BaseException`` handler (including inside a tuple) whose body neither
   re-``raise``s nor accounts for the error (an ``.inc(...)`` on an
   error counter, a ``.set_error(...)`` delivering it to a waiter, or an
   HTTP ``_reply(...)`` that the status counters see) is rejected: in a
   self-healing serving tier an error that is swallowed without a
   counter increment is an outage the dashboards cannot see. Handlers
   for specific exception types (``except ValueError: return default``)
   are fine — they are classification, not swallowing.
7. over ALL of ``spark_rapids_ml_tpu/`` (library code; the in-package
   ``scripts/`` helper dir is exempt, as are the repo-level ``scripts/``
   and ``examples/`` trees, which are outside the package): no bare
   ``print(`` calls — library output goes through the structured JSON
   logger (``obs.logging.get_logger``), which carries severity, the
   active trace id, and machine-parseable fields; a bare print is
   invisible to log shippers and severs the request identity the
   tracing layer threads through every queue.
8. over the clocked obs/ modules (``obs/tsdb.py``, ``obs/anomaly.py``,
   ``obs/incidents.py`` — the TSDB/detector/incident code paths): no
   direct ``time.time()`` or ``time.monotonic()`` CALLS. Those modules
   carry an injectable clock precisely so tests can drive hours of
   sampling, detection, and incident lifecycle with zero real sleeps —
   a wall-clock call buried in a helper silently forks the timeline
   from the injected one and the whole discipline rots. A *reference*
   as a default (``clock: Callable = time.time``) is the sanctioned
   spelling and passes; ``time.perf_counter()`` (duration
   self-measurement, not a timestamp) passes too.
9. over ``serve/batching.py`` (the pipelined micro-batcher): no
   host-sync calls — ``np.asarray(...)``, ``block_until_ready(...)``,
   or a direct ``.__array__()`` — anywhere in the worker loop except
   the DESIGNATED completion step (``_complete_batch``) and the
   submit-time dtype coercion at the door (``submit``, which runs on
   the caller's thread before any device value exists). The whole
   point of the async pipeline is that compute of batch N+1 overlaps
   the transfer of N+2 and the result fetch of N; one stray
   ``np.asarray`` on a device value inside the loop silently
   re-serializes all three, and nothing else would fail — latency
   would just quietly double. This rule makes that edit impossible to
   ship unnoticed.
11. over ``serve/server.py`` and ``serve/wire.py`` (the wire boundary):
   request-body decoding in the HTTP front end must route through the
   ``serve/wire.py`` decoders — a bare ``json.loads(...)`` call in
   ``serve/server.py`` is rejected (handler code parsing bodies by hand
   skips the negotiated binary format AND the parse-phase latency
   accounting) — and every ``decode_*`` function in ``serve/wire.py``
   must ``.observe(...)`` the parse latency: the protocol cost must
   stay a measured number, or the binary-vs-JSON win silently rots
   into an assertion.
10. over ``serve/admission.py`` and ``serve/scheduler.py`` (the
   multi-tenant admission/shed boundary): every **decision path** — a
   ``raise`` of a decision exception (``ShedLoad`` / ``QueueFull`` /
   ``OverQuota``) or a request resolution via ``.set_error(...)`` —
   must, in the same enclosing function, either increment a decision
   counter (``.inc(...)``) or file an audit span
   (``record_event``/``span``). A shed that is neither counted nor in
   the request's trace tree is a silent drop: the tenant sees a 503,
   the operator sees nothing, and the fairness contract becomes
   unauditable.
13. over ``serve/rollout.py`` and ``serve/registry.py`` (the rollout
   control plane): every **alias-flip path** — a function named
   ``alias``/``promote``/``rollback``/``abort``, or any function that
   *calls* an ``.alias(...)``/``.promote(...)`` mutation — must, in
   the same enclosing function, record a ``serve:rollout`` audit span
   (``span``/``record_event``) or increment a decision counter
   (``.inc(...)``). What a model alias points at IS what live traffic
   serves: a promote or rollback that neither the metrics nor the
   trace tree can see is an unauditable deployment change.
16. over ``spark_rapids_ml_tpu/parallel/distributed_*.py`` again: every
   public **fit** entry point (a ``distributed_*`` function with "fit"
   in its name that is not a ``*_kernel``) must enter a fit-step span —
   a ``.step(...)`` call (``current_run().step`` / a FitRun method)
   somewhere in its body, nested per-pass steppers included. A fit that
   never opens a step is invisible to ``/debug/fit``: no per-step
   device time, no rows/sec, no MFU attribution — the whole fit-path
   observability plane silently skips it.

New drivers and new models therefore cannot silently ship unobserved:
tier-1 runs this via ``tests/test_obs_reports.py``.

Pure ``ast`` — no jax import, no package import, so it runs anywhere in
milliseconds. Exit 0 = all instrumented; exit 1 = offenders listed on
stdout, one ``file:line name`` per line.
"""

from __future__ import annotations

import ast
import glob
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PARALLEL_GLOB = os.path.join(
    REPO, "spark_rapids_ml_tpu", "parallel", "distributed_*.py"
)
MODELS_GLOB = os.path.join(REPO, "spark_rapids_ml_tpu", "models", "*.py")
SPARK_GLOB = os.path.join(REPO, "spark_rapids_ml_tpu", "spark", "*.py")
SERVE_GLOB = os.path.join(REPO, "spark_rapids_ml_tpu", "serve", "*.py")
LIBRARY_ROOT = os.path.join(REPO, "spark_rapids_ml_tpu")
# rule 7 exemption: the in-package scripts/ dir holds operator shell
# helpers whose stdout IS their interface, like the repo-level scripts/.
PRINT_EXEMPT_DIRS = (os.path.join("spark_rapids_ml_tpu", "scripts"),)
# rule 8 scope: the obs/ modules whose correctness rests on the
# injectable-clock discipline (sampling, detection, incident lifecycle).
CLOCKED_OBS_FILES = tuple(
    os.path.join(REPO, "spark_rapids_ml_tpu", "obs", name)
    for name in ("tsdb.py", "anomaly.py", "incidents.py", "fitmon.py",
                 "federation.py", "forecast.py")
)
DECORATOR_NAME = "fit_instrumentation"
SERVING_DECORATOR = "observed_transform"
SERVING_PUBLIC_NAMES = frozenset(
    {"transform", "predict", "predict_proba"}
)


def _decorator_names(fn: ast.FunctionDef):
    for dec in fn.decorator_list:
        node = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(node, ast.Attribute):
            yield node.attr
        elif isinstance(node, ast.Name):
            yield node.id


def _is_entry_point(fn: ast.FunctionDef) -> bool:
    return (
        fn.name.startswith("distributed_")
        and not fn.name.endswith("_kernel")
    )


def _jax_aliases(tree: ast.Module):
    """Names the module binds to the jax package (``import jax``,
    ``import jax as j``) — so aliased ``j.jit`` can't evade the check."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax" or a.name.startswith("jax."):
                    aliases.add((a.asname or a.name).split(".")[0])
    return aliases or {"jax"}


def _jit_name_imports(tree: ast.Module):
    """Bare names bound to ``jax.jit`` via ``from jax import jit [as x]``."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "jit":
                    names.add(a.asname or a.name)
    return names


def _is_raw_jit(node: ast.AST, aliases, jit_names) -> bool:
    """A raw-jit reference in any spelling: ``jax.jit`` / ``j.jit``
    attribute access, or a bare name imported from jax — whether used as a
    decorator, a ``partial`` argument, or a direct call."""
    if (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id in aliases):
        return True
    return isinstance(node, ast.Name) and node.id in jit_names


def check_file(path: str):
    """Yield (lineno, name) for every uninstrumented entry point."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if not _is_entry_point(node):
            continue
        if DECORATOR_NAME not in set(_decorator_names(node)):
            yield node.lineno, node.name


def check_raw_jit(path: str):
    """Yield (lineno, context) for every raw ``jax.jit`` use anywhere in a
    driver module — decorator, ``partial`` argument, or direct call."""
    tree = ast.parse(open(path).read(), filename=path)
    aliases = _jax_aliases(tree)
    jit_names = _jit_name_imports(tree)
    for node in ast.walk(tree):
        if _is_raw_jit(node, aliases, jit_names):
            yield node.lineno, "raw jax.jit (use obs.tracked_jit)"


def _serving_names(path: str) -> frozenset:
    """The method names that count as serving entry points in one file.

    ``_transform`` counts only in ``spark/``: there the public
    ``transform`` lives on a (possibly external pyspark) base class, so
    the subclass hook is the only decoratable entry point. In ``models/``
    the public method itself is the entry point.
    """
    if os.sep + "spark" + os.sep in path:
        return SERVING_PUBLIC_NAMES | {"_transform"}
    return SERVING_PUBLIC_NAMES


def audit_serving_file(path: str):
    """One parse per file: ``(entry_point_count, offenders)`` where
    offenders is ``[(lineno, description), ...]``.

    An offender is a class-level serving entry point
    (``transform``/``predict``/``predict_proba``, ``_transform`` in
    ``spark/``) missing ``@observed_transform`` — OR a class-body
    *assignment* binding a serving name (``predict_proba = some_fn``),
    which ships the alias unobserved and invisible to decorator checks:
    serving entry points must be real decorated defs. Nested helper
    functions (pandas_udf closures named ``predict`` etc.) are not
    class-level and do not count.
    """
    tree = ast.parse(open(path).read(), filename=path)
    names = _serving_names(path)
    count = 0
    offenders = []
    for cls in tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in cls.body:
            if isinstance(node, ast.FunctionDef) and node.name in names:
                count += 1
                if SERVING_DECORATOR not in set(_decorator_names(node)):
                    offenders.append(
                        (node.lineno, f"{cls.name}.{node.name}")
                    )
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                # both `predict_proba = fn` and the annotated spelling
                # `predict_proba: Callable = fn` are alias loopholes
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for target in targets:
                    if isinstance(target, ast.Name) and target.id in names:
                        count += 1
                        offenders.append((
                            node.lineno,
                            f"{cls.name}.{target.id} (alias assignment — "
                            f"make it a decorated def)",
                        ))
    return count, offenders


def check_serving_file(path: str):
    """Yield (lineno, name) for every serving offender in one file."""
    _, offenders = audit_serving_file(path)
    yield from offenders


def check_serve_engine_file(path: str):
    """Rule 4: yield (lineno, description) for serving-engine offenders.

    Inside ``serve/``, raw ``jax.jit`` is rejected exactly as in the
    drivers, and so is any call that bypasses the ``@observed_transform``
    layer: invoking a model's ``._transform(...)`` hook directly, or
    calling a ``*_kernel`` function — engine batches must flow through
    the public decorated entry points or they ship unobserved.
    """
    tree = ast.parse(open(path).read(), filename=path)
    aliases = _jax_aliases(tree)
    jit_names = _jit_name_imports(tree)
    for node in ast.walk(tree):
        if _is_raw_jit(node, aliases, jit_names):
            yield node.lineno, "raw jax.jit (use obs.tracked_jit)"
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = None
        if isinstance(func, ast.Attribute):
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        if name == "_transform":
            yield (node.lineno,
                   "direct ._transform call (bypasses @observed_transform "
                   "— call the public transform)")
        elif name and name.endswith("_kernel"):
            yield (node.lineno,
                   f"direct {name} call (bypasses @observed_transform — "
                   "drive the model's public entry point)")


def _call_name(node: ast.Call):
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _contains_activate_call(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _call_name(node) == "activate":
            return True
    return False


def check_trace_handoffs(path: str):
    """Rule 5: yield (lineno, description) for TraceContext-handoff
    offenders in one serve/ module.

    * ``threading.Thread(...)`` (any spelling whose callee name is
      ``Thread``) — threads must be started via
      ``obs.tracectx.traced_thread`` so the child runs under a
      contextvars snapshot;
    * a ``.submit(...)`` call without a ``trace_ctx=`` keyword — the
      enqueue half of a queue handoff must carry the captured context;
    * ``.set_result(...)`` / ``.set_error(...)`` inside a function that
      never calls ``activate(...)`` — resolving a response future
      without restoring the request's context attributes whatever the
      resolution records to the wrong (or no) trace.

    Method *definitions* named ``set_result``/``set_error`` are fine —
    only call sites are judged, against their enclosing function.
    """
    tree = ast.parse(open(path).read(), filename=path)

    def visit(node, enclosing_fn):
        for child in ast.iter_child_nodes(node):
            fn = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) else enclosing_fn
            if isinstance(child, ast.Call):
                name = _call_name(child)
                if name == "Thread":
                    yield (child.lineno,
                           "raw threading.Thread (use "
                           "obs.tracectx.traced_thread — the handoff "
                           "must snapshot contextvars)")
                elif name == "submit":
                    kwargs = {k.arg for k in child.keywords}
                    if "trace_ctx" not in kwargs:
                        yield (child.lineno,
                               ".submit(...) without trace_ctx= (queue "
                               "handoff drops the TraceContext — pass "
                               "the captured context)")
                elif name in ("set_result", "set_error"):
                    if enclosing_fn is None or not \
                            _contains_activate_call(enclosing_fn):
                        yield (child.lineno,
                               f".{name}(...) without a TraceContext "
                               "restore (wrap the resolution in "
                               "tracectx.activate(req.trace_ctx))")
            yield from visit(child, fn)

    yield from visit(tree, None)


# Calls that count as "accounting for" a swallowed exception in rule 6:
# incrementing an error counter, delivering the error to the waiting
# request, or replying over HTTP (every _reply status is counted).
_ACCOUNTING_CALLS = frozenset({"inc", "set_error", "_reply"})
_BROAD_EXCEPTIONS = frozenset({"Exception", "BaseException"})


def _exception_names(node):
    """The exception names an ``except`` clause catches (handles bare
    names, dotted names, and tuples)."""
    if node is None:
        return []
    nodes = node.elts if isinstance(node, ast.Tuple) else [node]
    names = []
    for n in nodes:
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
    return names


def _handler_accounts(handler: ast.ExceptHandler) -> bool:
    """Whether the handler body re-raises or accounts for the error."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in _ACCOUNTING_CALLS:
                return True
    return False


def check_exception_hygiene(path: str):
    """Rule 6: yield (lineno, description) for silent exception swallows
    in one serve/ module."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            yield (node.lineno,
                   "bare except: (name the exceptions, re-raise, or "
                   "count the error)")
            continue
        caught = _exception_names(node.type)
        if not any(name in _BROAD_EXCEPTIONS for name in caught):
            continue
        if not _handler_accounts(node):
            yield (node.lineno,
                   f"except {'/'.join(caught)} swallow without an error-"
                   "counter .inc(), .set_error(), _reply(), or re-raise")


def check_print_calls(path: str):
    """Rule 7: yield (lineno, description) for every bare ``print(``
    call in one library module.

    Pure AST — only actual ``print(...)`` CALLS count; the word inside
    a string literal (e.g. generated subprocess code) does not. Library
    output must go through ``obs.logging`` so it carries severity and
    the active trace id."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            yield (node.lineno,
                   "bare print( in library code (use "
                   "obs.logging.get_logger(...) — structured, leveled, "
                   "trace-id-stamped)")


# rule 8: wall-clock reads forbidden in clocked obs/ code paths.
_WALL_CLOCK_NAMES = frozenset({"time", "monotonic"})


def _time_aliases(tree: ast.Module):
    """Names the module binds to the time module (``import time``,
    ``import time as t``) — aliased ``t.time()`` can't evade the
    check."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    aliases.add(a.asname or a.name)
    return aliases or {"time"}


def _wall_clock_name_imports(tree: ast.Module):
    """Bare names bound via ``from time import time/monotonic [as x]``."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in _WALL_CLOCK_NAMES:
                    names.add(a.asname or a.name)
    return names


def check_clock_injection(path: str):
    """Rule 8: yield (lineno, description) for every direct
    ``time.time()``/``time.monotonic()`` CALL in a clocked obs/ module.

    Only ``ast.Call`` nodes count: the default-argument *reference*
    (``clock: Callable[[], float] = time.time``) is exactly how the
    injectable clock is supposed to be spelled, and
    ``time.perf_counter()`` (self-measured durations, not timestamps)
    is exempt.
    """
    tree = ast.parse(open(path).read(), filename=path)
    aliases = _time_aliases(tree)
    bare_names = _wall_clock_name_imports(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        offender = None
        if (isinstance(func, ast.Attribute)
                and func.attr in _WALL_CLOCK_NAMES
                and isinstance(func.value, ast.Name)
                and func.value.id in aliases):
            offender = f"time.{func.attr}"
        elif isinstance(func, ast.Name) and func.id in bare_names:
            offender = f"time.{func.id} (imported bare)"
        if offender:
            yield (node.lineno,
                   f"direct {offender}() call bypasses the injectable "
                   "clock (take/pass a clock= / now= instead — this "
                   "code path must be drivable by tests with zero "
                   "real sleeps)")


# rule 9: host-sync call names forbidden in the batcher's worker loop,
# and the only functions allowed to use them — the designated completion
# step, plus the caller-thread dtype coercion at the submission door.
_HOST_SYNC_CALLS = frozenset({"asarray", "block_until_ready", "__array__"})
_HOST_SYNC_ALLOWED_FUNCS = frozenset({"_complete_batch", "submit"})
BATCHING_FILE = os.path.join(
    REPO, "spark_rapids_ml_tpu", "serve", "batching.py"
)


def check_pipeline_sync(path: str):
    """Rule 9: yield (lineno, description) for every host-sync call in
    ``serve/batching.py`` outside the designated completion step.

    Judged per enclosing function (like rule 5): a call whose name is
    ``asarray`` / ``block_until_ready`` / ``__array__`` — any spelling,
    ``np.asarray`` or a bare import — inside any function except
    ``_complete_batch`` (THE sync point) or ``submit`` (caller-thread
    coercion) is an offender. A host sync smuggled into the stage or
    dispatch step would silently re-serialize the pipeline.
    """
    tree = ast.parse(open(path).read(), filename=path)

    def visit(node, enclosing_name):
        for child in ast.iter_child_nodes(node):
            name = enclosing_name
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
            if isinstance(child, ast.Call):
                call = _call_name(child)
                if call in _HOST_SYNC_CALLS and \
                        enclosing_name not in _HOST_SYNC_ALLOWED_FUNCS:
                    yield (child.lineno,
                           f"host sync {call}(...) outside the designated "
                           "completion step (move it into "
                           "_complete_batch — a sync in the stage/"
                           "dispatch path re-serializes the pipeline)")
            yield from visit(child, name)

    yield from visit(tree, None)


# rule 10: decision exceptions and the accounting calls that make a
# decision path attributable instead of a silent drop.
_DECISION_EXCEPTIONS = frozenset({"ShedLoad", "QueueFull", "OverQuota"})
_DECISION_ACCOUNTING = frozenset({"inc", "record_event", "span"})
ADMISSION_FILES = tuple(
    os.path.join(REPO, "spark_rapids_ml_tpu", "serve", name)
    for name in ("admission.py", "scheduler.py")
)


def _raised_exception_name(node: ast.Raise):
    if node.exc is None:
        return None
    target = node.exc.func if isinstance(node.exc, ast.Call) else node.exc
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Name):
        return target.id
    return None


def check_admission_decisions(path: str):
    """Rule 10: yield (lineno, description) for every unaccounted
    admission/shed decision path in one admission/scheduler module.

    A decision path is a ``raise`` of a decision exception
    (``ShedLoad``/``QueueFull``/``OverQuota``) or a ``.set_error(...)``
    resolution; judged per enclosing function (like rules 5/9): the
    SAME function must carry a decision-counter ``.inc(...)`` or an
    audit ``record_event``/``span`` call — a shed the metrics and the
    trace tree both miss is a silent drop."""
    tree = ast.parse(open(path).read(), filename=path)

    def fn_accounts(fn) -> bool:
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and _call_name(node) in _DECISION_ACCOUNTING):
                return True
        return False

    def visit(node, enclosing_fn):
        for child in ast.iter_child_nodes(node):
            fn = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) else enclosing_fn
            decision = None
            if isinstance(child, ast.Raise):
                name = _raised_exception_name(child)
                if name in _DECISION_EXCEPTIONS:
                    decision = f"raise {name}"
            elif (isinstance(child, ast.Call)
                  and _call_name(child) == "set_error"):
                decision = ".set_error(...)"
            if decision is not None and (
                    enclosing_fn is None or not fn_accounts(enclosing_fn)):
                yield (child.lineno,
                       f"admission/shed decision ({decision}) without a "
                       "decision-counter .inc(...) or audit "
                       "record_event/span in the same function — a shed "
                       "nobody can see is a silent drop (rule 10)")
            yield from visit(child, fn)

    yield from visit(tree, None)


# rule 13: the rollout control plane — alias promote/rollback/abort
# paths must be audit-spanned or decision-counted in the same function.
ROLLOUT_FILES = tuple(
    os.path.join(REPO, "spark_rapids_ml_tpu", "serve", name)
    for name in ("rollout.py", "registry.py")
)
_ROLLOUT_MUTATOR_NAMES = frozenset({"alias", "promote", "rollback",
                                    "abort"})
_ROLLOUT_MUTATION_CALLS = frozenset({"alias", "promote"})
_ROLLOUT_ACCOUNTING = frozenset({"inc", "record_event", "span"})


def check_rollout_audit(path: str):
    """Rule 13: yield (lineno, description) for every unaudited
    alias-flip path in one rollout/registry module.

    A flip path is a function DEF named ``alias``/``promote``/
    ``rollback``/``abort`` or a function whose body calls an
    ``.alias(...)``/``.promote(...)`` mutation; the same function must
    carry a ``span``/``record_event`` audit call or a decision-counter
    ``.inc(...)`` — an alias mutation nobody can see is an unauditable
    deployment change."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        is_flip_path = node.name in _ROLLOUT_MUTATOR_NAMES
        if not is_flip_path:
            for child in ast.walk(node):
                if (isinstance(child, ast.Call)
                        and _call_name(child) in _ROLLOUT_MUTATION_CALLS):
                    is_flip_path = True
                    break
        if not is_flip_path:
            continue
        accounts = any(
            isinstance(child, ast.Call)
            and _call_name(child) in _ROLLOUT_ACCOUNTING
            for child in ast.walk(node)
        )
        if not accounts:
            yield (node.lineno,
                   f"alias-flip path {node.name}() without a "
                   "serve:rollout audit span/record_event or a "
                   "decision-counter .inc(...) in the same function — "
                   "an alias mutation nobody can see is an unauditable "
                   "deployment change (rule 13)")


# rule 14: the zero-cold-start tier — every executable-cache decision
# path (hit/miss/store/evict/invalidate) and every autoscale replica
# mutation must be counted or audit-spanned in the same function. A
# cache that silently misses is a restart paying full recompiles with
# nothing on the dashboard; a replica-count change nobody can see is
# an unauditable capacity change.
CACHE_AUTOSCALE_FILES = (
    os.path.join(REPO, "spark_rapids_ml_tpu", "obs", "aotcache.py"),
    os.path.join(REPO, "spark_rapids_ml_tpu", "serve", "autoscale.py"),
)
_CACHE_DECISION_NAMES = frozenset({"load", "store"})
_CACHE_DECISION_PREFIXES = ("evict", "invalidate", "scale_up",
                            "scale_down")
_SCALE_MUTATION_CALLS = frozenset({"scale_replicas"})
# the sanctioned accounting spellings: a metrics .inc / audit span
# directly, or the cache module's own counting helpers (which resolve
# to the sparkml_serve_cache_* counters + serve:cache events)
_CACHE_ACCOUNTING = frozenset({"inc", "record_event", "span",
                               "_count", "_count_error", "_audit"})


def check_cache_autoscale_audit(path: str):
    """Rule 14: yield (lineno, description) for every unaccounted
    cache/autoscale decision path in one aotcache/autoscale module.

    A decision path is a function DEF named ``load``/``store`` (or
    prefixed ``evict``/``invalidate``/``scale_up``/``scale_down``,
    underscore-insensitive), or any function whose body calls the
    ``.scale_replicas(...)`` replica mutation; the same function must
    carry a counter ``.inc(...)``, an audit ``record_event``/``span``,
    or one of the cache module's ``_count``/``_count_error``/``_audit``
    accounting helpers."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        bare = node.name.lstrip("_")
        is_decision = (bare in _CACHE_DECISION_NAMES
                       or bare.startswith(_CACHE_DECISION_PREFIXES))
        if not is_decision:
            for child in ast.walk(node):
                if (isinstance(child, ast.Call)
                        and _call_name(child) in _SCALE_MUTATION_CALLS):
                    is_decision = True
                    break
        if not is_decision:
            continue
        accounts = any(
            isinstance(child, ast.Call)
            and _call_name(child) in _CACHE_ACCOUNTING
            for child in ast.walk(node)
        )
        if not accounts:
            yield (node.lineno,
                   f"cache/autoscale decision path {node.name}() "
                   "without a counter .inc(...), audit "
                   "record_event/span, or cache accounting helper in "
                   "the same function — a silent cache miss or an "
                   "unaudited replica-count change is invisible "
                   "capacity drift (rule 14)")


# rule 15: the resource ledger (obs/accounting.py) is the number the
# tiering/eviction and predictive-autoscaling controllers will trust.
# A ledger mutation that leaves no metrics trail is a ledger that can
# silently diverge from the devices — every charge/release/reconcile
# path must announce itself.
ACCOUNTING_FILE = os.path.join(
    REPO, "spark_rapids_ml_tpu", "obs", "accounting.py"
)
_LEDGER_MUTATION_PREFIXES = ("charge", "release", "reconcile",
                             "retire", "revive", "note")
# same sanctioned spellings as rule 14: a counter .inc / audit
# record_event/span directly, or a module counting helper
_LEDGER_ACCOUNTING = frozenset({"inc", "record_event", "span",
                                "_count", "_count_error", "_audit"})


def check_ledger_audit(path: str):
    """Rule 15: yield (lineno, description) for every silent ledger
    mutation path in the resource-accounting module.

    A mutation path is any function DEF whose name starts with
    ``charge``/``release``/``reconcile``/``retire``/``revive``/``note``
    (underscore-insensitive — ``_charge_attribution`` counts); the same
    function must carry a counter ``.inc(...)``, an audit
    ``record_event``/``span``, or a module accounting helper."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        bare = node.name.lstrip("_")
        if not bare.startswith(_LEDGER_MUTATION_PREFIXES):
            continue
        accounts = any(
            isinstance(child, ast.Call)
            and _call_name(child) in _LEDGER_ACCOUNTING
            for child in ast.walk(node)
        )
        if not accounts:
            yield (node.lineno,
                   f"ledger mutation path {node.name}() without a "
                   "counter .inc(...), audit record_event/span, or "
                   "accounting helper in the same function — a silent "
                   "ledger mutation is a cost number nobody can "
                   "cross-check against the devices (rule 15)")


# rule 17: the tiering lifecycle (serve/tiering.py) is the plane that
# unloads models from the devices — every tier-transition path must
# carry a decision counter .inc or a serve:tiering audit span in the
# same function. A model that goes COLD with nothing on the dashboard
# is capacity that vanished unauditably; a reactivation nobody can see
# is an unexplainable first-hit latency cliff.
TIERING_FILE = os.path.join(
    REPO, "spark_rapids_ml_tpu", "serve", "tiering.py"
)
_TIER_TRANSITION_NAMES = frozenset({"pin", "unpin"})
_TIER_TRANSITION_PREFIXES = ("deactivate", "reactivate", "evaluate",
                             "transition", "tick")
_TIER_MUTATION_CALLS = frozenset({"deactivate", "reactivate",
                                  "_deactivate", "_reactivate"})
# the sanctioned accounting spellings: a metrics .inc / audit span
# directly, or the tiering module's own _event funnel (which resolves
# to sparkml_serve_tiering_total + serve:tiering audit events)
_TIER_ACCOUNTING = frozenset({"inc", "record_event", "span", "_event",
                              "_count", "_audit"})


def check_tiering_transitions(path: str):
    """Rule 17: yield (lineno, description) for every unaccounted
    tier-transition path in the tiering module.

    A transition path is a function DEF named ``pin``/``unpin`` (or
    prefixed ``deactivate``/``reactivate``/``evaluate``/``transition``/
    ``tick``, underscore-insensitive), or any function whose body calls
    a ``deactivate``/``reactivate`` lifecycle mutation; the same
    function must carry a decision counter ``.inc(...)``, an audit
    ``record_event``/``span``, or the module's ``_event`` accounting
    funnel."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        bare = node.name.lstrip("_")
        is_transition = (bare in _TIER_TRANSITION_NAMES
                         or bare.startswith(_TIER_TRANSITION_PREFIXES))
        if not is_transition:
            for child in ast.walk(node):
                if (isinstance(child, ast.Call)
                        and _call_name(child) in _TIER_MUTATION_CALLS):
                    is_transition = True
                    break
        if not is_transition:
            continue
        accounts = any(
            isinstance(child, ast.Call)
            and _call_name(child) in _TIER_ACCOUNTING
            for child in ast.walk(node)
        )
        if not accounts:
            yield (node.lineno,
                   f"tier-transition path {node.name}() without a "
                   "decision counter .inc(...) or serve:tiering audit "
                   "record_event/span in the same function — a model "
                   "that changes tier with nothing on the dashboard is "
                   "unauditable capacity drift (rule 17)")


# rule 18: the fleet federation + predictive signal plane
# (obs/federation.py, obs/forecast.py) is what a fleet operator trusts
# to SEE other hosts — every peer-poll outcome (ok/stale/unreachable),
# every merged delta, every incident-dedup decision, and every
# predictive-autoscale shadow/actuate consult must carry a counter
# .inc / span / audit event in the same function. A silently-failed
# poll is a host that looks healthy while dark; an uncounted shadow
# decision makes the shadow-mode evidence trail worthless.
FEDERATION_FILES = (
    os.path.join(REPO, "spark_rapids_ml_tpu", "obs", "federation.py"),
    os.path.join(REPO, "spark_rapids_ml_tpu", "obs", "forecast.py"),
)
_FLEET_DECISION_NAMES = frozenset({"fleet_export", "poll_once", "tick"})
_FLEET_DECISION_PREFIXES = ("poll", "merge", "dedup", "shadow",
                            "actuate")
_FLEET_MUTATION_CALLS = frozenset({"predictive_scale_up",
                                   "scale_replicas"})
# same sanctioned accounting spellings as rules 14/15/17
_FLEET_ACCOUNTING = frozenset({"inc", "record_event", "span",
                               "_count", "_count_error", "_audit"})


def check_federation_signals(path: str):
    """Rule 18: yield (lineno, description) for every unaccounted
    federation/forecast decision path in one module.

    A decision path is a function DEF named
    ``fleet_export``/``poll_once``/``tick`` (or prefixed ``poll``/
    ``merge``/``dedup``/``shadow``/``actuate``,
    underscore-insensitive), or any function whose body calls the
    ``predictive_scale_up``/``scale_replicas`` replica mutations; the
    same function must carry a counter ``.inc(...)``, an audit
    ``record_event``/``span``, or a module ``_count``/``_count_error``/
    ``_audit`` accounting helper."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        bare = node.name.lstrip("_")
        is_decision = (bare in _FLEET_DECISION_NAMES
                       or bare.startswith(_FLEET_DECISION_PREFIXES))
        if not is_decision:
            for child in ast.walk(node):
                if (isinstance(child, ast.Call)
                        and _call_name(child) in _FLEET_MUTATION_CALLS):
                    is_decision = True
                    break
        if not is_decision:
            continue
        accounts = any(
            isinstance(child, ast.Call)
            and _call_name(child) in _FLEET_ACCOUNTING
            for child in ast.walk(node)
        )
        if not accounts:
            yield (node.lineno,
                   f"federation/forecast decision path {node.name}() "
                   "without a counter .inc(...), audit "
                   "record_event/span, or accounting helper in the "
                   "same function — an uncounted peer poll or "
                   "predictive consult is a fleet view that can lie "
                   "silently (rule 18)")


# rule 11: the wire boundary — server body decoding must route through
# serve/wire.py, whose decoders must record the parse-phase latency.
SERVER_FILE = os.path.join(
    REPO, "spark_rapids_ml_tpu", "serve", "server.py"
)
WIRE_FILE = os.path.join(
    REPO, "spark_rapids_ml_tpu", "serve", "wire.py"
)


def _json_aliases(tree: ast.Module):
    """Names the module binds to the json module (``import json``,
    ``import json as j``) — aliased ``j.loads`` can't evade the check."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "json":
                    aliases.add(a.asname or a.name)
    return aliases or {"json"}


def _json_loads_names(tree: ast.Module):
    """Bare names bound via ``from json import loads [as x]``."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "json":
            for a in node.names:
                if a.name == "loads":
                    names.add(a.asname or a.name)
    return names


def check_server_body_decoding(path: str):
    """Rule 11a: yield (lineno, description) for every ``json.loads``
    call in ``serve/server.py`` — request bodies must decode through
    ``serve.wire`` (which negotiates the binary format and records the
    parse-phase latency), never by hand in handler code."""
    tree = ast.parse(open(path).read(), filename=path)
    aliases = _json_aliases(tree)
    bare = _json_loads_names(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        offender = (
            (isinstance(func, ast.Attribute) and func.attr == "loads"
             and isinstance(func.value, ast.Name)
             and func.value.id in aliases)
            or (isinstance(func, ast.Name) and func.id in bare)
        )
        if offender:
            yield (node.lineno,
                   "bare json.loads on a request body (route through "
                   "serve/wire.py decode_body — the wire boundary "
                   "negotiates the binary format and records the "
                   "parse-phase latency)")


def check_wire_parse_metrics(path: str):
    """Rule 11b: yield (lineno, description) for every module-level
    ``decode_*`` function in ``serve/wire.py`` that never
    ``.observe(...)``s — a decoder that stops recording the parse stage
    turns the measured protocol win back into an assertion."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        # the leaf REQUEST-body decoders (decode_request,
        # decode_json_request, future decode_*_request); decode_body is
        # the dispatcher and decode_response is the client side — no
        # parse stage of their own to record
        if not (node.name.startswith("decode_")
                and node.name.endswith("request")):
            continue
        observes = any(
            isinstance(n, ast.Call) and _call_name(n) == "observe"
            for n in ast.walk(node)
        )
        if not observes:
            yield (node.lineno,
                   f"{node.name} decodes a request body without an "
                   ".observe(...) of the parse-phase latency "
                   "(sparkml_serve_parse_seconds) — the wire cost must "
                   "stay measured")


# rule 12: device selection in serve/ must route through placement.py —
# the multi-replica tier's one device-enumeration chokepoint. A
# hard-coded jax.devices()[0] (or an implicit default-device
# jax.device_put) silently pins serving work to device 0, which is
# exactly the single-chip bottleneck the replica tier removed.
PLACEMENT_FILE = os.path.join(
    REPO, "spark_rapids_ml_tpu", "serve", "placement.py"
)
_DEVICE_ENUM_CALLS = frozenset({"devices", "local_devices"})


def _jax_aliases(tree: ast.Module):
    """Names the module binds to the jax module (``import jax``,
    ``import jax as j``) — aliased ``j.devices()`` can't evade the
    check."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    aliases.add(a.asname or a.name)
    return aliases or {"jax"}


def _jax_name_imports(tree: ast.Module, wanted) -> set:
    """Bare names bound via ``from jax import devices/device_put``."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name in wanted:
                    names.add(a.asname or a.name)
    return names


def check_device_selection(path: str):
    """Rule 12: yield (lineno, description) for device-selection calls
    in a serve/ module other than placement.py.

    Offenders: any ``jax.devices()`` / ``jax.local_devices()`` call
    (including subscripted ``jax.devices()[0]`` — the call itself is
    the offense), and ``jax.device_put`` with no explicit device/
    sharding target (fewer than two positional args and no ``device=``
    kwarg) — implicit default-device placement pins work to device 0
    behind the placement tier's back."""
    tree = ast.parse(open(path).read(), filename=path)
    aliases = _jax_aliases(tree)
    bare_enum = _jax_name_imports(tree, _DEVICE_ENUM_CALLS)
    bare_put = _jax_name_imports(tree, {"device_put"})
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        enum = (
            (isinstance(func, ast.Attribute)
             and func.attr in _DEVICE_ENUM_CALLS
             and isinstance(func.value, ast.Name)
             and func.value.id in aliases)
            or (isinstance(func, ast.Name) and func.id in bare_enum)
        )
        if enum:
            yield (node.lineno,
                   "device enumeration in serve/ outside placement.py "
                   "(route through serve.placement.serving_devices — "
                   "a hard-coded jax.devices()[0] pins the tier to one "
                   "chip)")
            continue
        put = (
            (isinstance(func, ast.Attribute) and func.attr == "device_put"
             and isinstance(func.value, ast.Name)
             and func.value.id in aliases)
            or (isinstance(func, ast.Name) and func.id in bare_put)
        )
        if put and len(node.args) < 2 and not any(
                kw.arg == "device" for kw in node.keywords):
            yield (node.lineno,
                   "implicit default-device jax.device_put in serve/ "
                   "(pass the replica's device from serve/placement.py "
                   "— default placement pins work to device 0)")


def check_fit_step_monitoring(path: str):
    """Rule 16: every public fit entry point must enter a fit-step span.

    A ``.step(...)`` attribute call anywhere inside the function body
    (``ast.walk``, so nested per-pass steppers like GLM's IRLS closure
    count) satisfies the rule — that is the ``current_run().step``
    seam the fitmon plane meters. A fit without one produces no
    per-step device time, rows/sec, or MFU in ``/debug/fit``."""
    tree = ast.parse(open(path).read(), filename=path)
    for fn in tree.body:
        if not isinstance(fn, ast.FunctionDef):
            continue
        if not _is_entry_point(fn) or "fit" not in fn.name:
            continue
        has_step = any(
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "step"
            for node in ast.walk(fn)
        )
        if not has_step:
            yield (fn.lineno,
                   f"{fn.name} (fit entry point never enters a fitmon "
                   "step — wrap the blocked kernel pass in "
                   "current_run().step(...))")


def library_files():
    """Every .py under the package, minus the exempt helper dirs."""
    out = []
    for root, _dirs, files in os.walk(LIBRARY_ROOT):
        rel_root = os.path.relpath(root, REPO)
        # component-wise: "spark_rapids_ml_tpu/scripts_v2" must NOT
        # match the "spark_rapids_ml_tpu/scripts" exemption
        if any(rel_root == d or rel_root.startswith(d + os.sep)
               for d in PRINT_EXEMPT_DIRS):
            continue
        for fname in sorted(files):
            if fname.endswith(".py"):
                out.append(os.path.join(root, fname))
    return sorted(out)


def main() -> int:
    files = sorted(glob.glob(PARALLEL_GLOB))
    if not files:
        print("ERROR: no parallel/distributed_*.py files found")
        return 1
    serving_files = sorted(
        path
        for path in glob.glob(MODELS_GLOB) + glob.glob(SPARK_GLOB)
        if os.path.basename(path) not in ("__init__.py", "_compat.py")
    )
    if not serving_files:
        print("ERROR: no models/ or spark/ files found")
        return 1
    offenders = []
    checked = 0
    for path in files:
        rel = os.path.relpath(path, REPO)
        tree = ast.parse(open(path).read(), filename=path)
        entry_points = [
            n for n in tree.body
            if isinstance(n, ast.FunctionDef) and _is_entry_point(n)
        ]
        checked += len(entry_points)
        for lineno, name in check_file(path):
            offenders.append(f"{rel}:{lineno} {name} "
                             f"(missing @{DECORATOR_NAME})")
        for lineno, why in check_raw_jit(path):
            offenders.append(f"{rel}:{lineno} {why}")
        for lineno, why in check_fit_step_monitoring(path):
            offenders.append(f"{rel}:{lineno} {why}")
    serving_checked = 0
    for path in serving_files:
        rel = os.path.relpath(path, REPO)
        count, serving_offenders = audit_serving_file(path)
        serving_checked += count
        for lineno, name in serving_offenders:
            offenders.append(f"{rel}:{lineno} {name} "
                             f"(missing @{SERVING_DECORATOR})")
    serve_files = sorted(
        path for path in glob.glob(SERVE_GLOB)
        if os.path.basename(path) != "__init__.py"
    )
    for path in serve_files:
        rel = os.path.relpath(path, REPO)
        for lineno, why in check_serve_engine_file(path):
            offenders.append(f"{rel}:{lineno} {why}")
        for lineno, why in check_trace_handoffs(path):
            offenders.append(f"{rel}:{lineno} {why}")
        for lineno, why in check_exception_hygiene(path):
            offenders.append(f"{rel}:{lineno} {why}")
        if os.path.abspath(path) != os.path.abspath(PLACEMENT_FILE):
            for lineno, why in check_device_selection(path):
                offenders.append(f"{rel}:{lineno} {why}")
    lib_files = library_files()
    for path in lib_files:
        rel = os.path.relpath(path, REPO)
        for lineno, why in check_print_calls(path):
            offenders.append(f"{rel}:{lineno} {why}")
    clocked_files = [p for p in CLOCKED_OBS_FILES if os.path.exists(p)]
    for path in clocked_files:
        rel = os.path.relpath(path, REPO)
        for lineno, why in check_clock_injection(path):
            offenders.append(f"{rel}:{lineno} {why}")
    if os.path.exists(BATCHING_FILE):
        rel = os.path.relpath(BATCHING_FILE, REPO)
        for lineno, why in check_pipeline_sync(BATCHING_FILE):
            offenders.append(f"{rel}:{lineno} {why}")
    admission_files = [p for p in ADMISSION_FILES if os.path.exists(p)]
    for path in admission_files:
        rel = os.path.relpath(path, REPO)
        for lineno, why in check_admission_decisions(path):
            offenders.append(f"{rel}:{lineno} {why}")
    if os.path.exists(SERVER_FILE):
        rel = os.path.relpath(SERVER_FILE, REPO)
        for lineno, why in check_server_body_decoding(SERVER_FILE):
            offenders.append(f"{rel}:{lineno} {why}")
    if os.path.exists(WIRE_FILE):
        rel = os.path.relpath(WIRE_FILE, REPO)
        for lineno, why in check_wire_parse_metrics(WIRE_FILE):
            offenders.append(f"{rel}:{lineno} {why}")
    rollout_files = [p for p in ROLLOUT_FILES if os.path.exists(p)]
    for path in rollout_files:
        rel = os.path.relpath(path, REPO)
        for lineno, why in check_rollout_audit(path):
            offenders.append(f"{rel}:{lineno} {why}")
    cache_files = [p for p in CACHE_AUTOSCALE_FILES if os.path.exists(p)]
    for path in cache_files:
        rel = os.path.relpath(path, REPO)
        for lineno, why in check_cache_autoscale_audit(path):
            offenders.append(f"{rel}:{lineno} {why}")
    if os.path.exists(ACCOUNTING_FILE):
        rel = os.path.relpath(ACCOUNTING_FILE, REPO)
        for lineno, why in check_ledger_audit(ACCOUNTING_FILE):
            offenders.append(f"{rel}:{lineno} {why}")
    if os.path.exists(TIERING_FILE):
        rel = os.path.relpath(TIERING_FILE, REPO)
        for lineno, why in check_tiering_transitions(TIERING_FILE):
            offenders.append(f"{rel}:{lineno} {why}")
    federation_files = [p for p in FEDERATION_FILES if os.path.exists(p)]
    for path in federation_files:
        rel = os.path.relpath(path, REPO)
        for lineno, why in check_federation_signals(path):
            offenders.append(f"{rel}:{lineno} {why}")
    if offenders:
        print(f"{len(offenders)} instrumentation offender(s):")
        for line in offenders:
            print(f"  {line}")
        return 1
    print(
        f"OK: {checked} distributed entry point(s) across {len(files)} "
        f"driver module(s) all instrumented; all jit sites tracked; "
        f"{serving_checked} serving entry point(s) across "
        f"{len(serving_files)} models/spark module(s) all instrumented; "
        f"{len(serve_files)} serve/ module(s) clean (no raw jit, no "
        f"transform bypasses, all queue/thread handoffs carry their "
        f"TraceContext, no silent exception swallows); "
        f"{len(lib_files)} library module(s) free of bare print(; "
        f"{len(clocked_files)} clocked obs module(s) free of direct "
        f"wall-clock calls; serve/batching.py host-syncs only in its "
        f"designated completion step; {len(admission_files)} "
        f"admission/scheduler module(s) with every shed/admission "
        f"decision counted or audit-spanned; request-body decoding "
        f"routed through serve/wire.py with the parse stage measured; "
        f"serve/ device selection routed through serve/placement.py; "
        f"{len(rollout_files)} rollout/registry module(s) with every "
        f"alias promote/rollback/abort path audit-spanned or "
        f"decision-counted; {len(cache_files)} cache/autoscale "
        f"module(s) with every hit/miss/evict/invalidate and "
        f"scale-up/scale-down decision counted or audit-spanned; "
        f"cost-ledger mutation paths all counted or audit-spanned; "
        f"every fit entry point enters a fitmon step span; "
        f"tiering tier-transition paths all counted or audit-spanned; "
        f"{len(federation_files)} federation/forecast module(s) with "
        f"every peer-poll, merge, incident-dedup, and predictive "
        f"shadow/actuate path counted or audit-spanned"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
