#!/usr/bin/env python
"""Static check: every distributed driver uses the shared instrumentation.

Two rules over ``spark_rapids_ml_tpu/parallel/distributed_*.py``:

1. every module-level public entry point (a ``distributed_*`` function that
   is not a ``*_kernel``) carries the ``@fit_instrumentation(...)``
   decorator from ``spark_rapids_ml_tpu.obs``;
2. no jitted entry point uses raw ``jax.jit`` — every jit decoration (and
   every ``jax.jit(...)`` call) must go through ``obs.tracked_jit`` /
   ``track_compiles``, so compile time, recompiles, and HLO cost analysis
   are observable for every driver program.

New drivers therefore cannot silently ship unobserved: tier-1 runs this
via ``tests/test_obs_reports.py``.

Pure ``ast`` — no jax import, no package import, so it runs anywhere in
milliseconds. Exit 0 = all instrumented; exit 1 = offenders listed on
stdout, one ``file:line name`` per line.
"""

from __future__ import annotations

import ast
import glob
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PARALLEL_GLOB = os.path.join(
    REPO, "spark_rapids_ml_tpu", "parallel", "distributed_*.py"
)
DECORATOR_NAME = "fit_instrumentation"


def _decorator_names(fn: ast.FunctionDef):
    for dec in fn.decorator_list:
        node = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(node, ast.Attribute):
            yield node.attr
        elif isinstance(node, ast.Name):
            yield node.id


def _is_entry_point(fn: ast.FunctionDef) -> bool:
    return (
        fn.name.startswith("distributed_")
        and not fn.name.endswith("_kernel")
    )


def _jax_aliases(tree: ast.Module):
    """Names the module binds to the jax package (``import jax``,
    ``import jax as j``) — so aliased ``j.jit`` can't evade the check."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax" or a.name.startswith("jax."):
                    aliases.add((a.asname or a.name).split(".")[0])
    return aliases or {"jax"}


def _jit_name_imports(tree: ast.Module):
    """Bare names bound to ``jax.jit`` via ``from jax import jit [as x]``."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "jit":
                    names.add(a.asname or a.name)
    return names


def _is_raw_jit(node: ast.AST, aliases, jit_names) -> bool:
    """A raw-jit reference in any spelling: ``jax.jit`` / ``j.jit``
    attribute access, or a bare name imported from jax — whether used as a
    decorator, a ``partial`` argument, or a direct call."""
    if (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id in aliases):
        return True
    return isinstance(node, ast.Name) and node.id in jit_names


def check_file(path: str):
    """Yield (lineno, name) for every uninstrumented entry point."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if not _is_entry_point(node):
            continue
        if DECORATOR_NAME not in set(_decorator_names(node)):
            yield node.lineno, node.name


def check_raw_jit(path: str):
    """Yield (lineno, context) for every raw ``jax.jit`` use anywhere in a
    driver module — decorator, ``partial`` argument, or direct call."""
    tree = ast.parse(open(path).read(), filename=path)
    aliases = _jax_aliases(tree)
    jit_names = _jit_name_imports(tree)
    for node in ast.walk(tree):
        if _is_raw_jit(node, aliases, jit_names):
            yield node.lineno, "raw jax.jit (use obs.tracked_jit)"


def main() -> int:
    files = sorted(glob.glob(PARALLEL_GLOB))
    if not files:
        print("ERROR: no parallel/distributed_*.py files found")
        return 1
    offenders = []
    checked = 0
    for path in files:
        rel = os.path.relpath(path, REPO)
        tree = ast.parse(open(path).read(), filename=path)
        entry_points = [
            n for n in tree.body
            if isinstance(n, ast.FunctionDef) and _is_entry_point(n)
        ]
        checked += len(entry_points)
        for lineno, name in check_file(path):
            offenders.append(f"{rel}:{lineno} {name} "
                             f"(missing @{DECORATOR_NAME})")
        for lineno, why in check_raw_jit(path):
            offenders.append(f"{rel}:{lineno} {why}")
    if offenders:
        print(f"{len(offenders)} instrumentation offender(s):")
        for line in offenders:
            print(f"  {line}")
        return 1
    print(
        f"OK: {checked} distributed entry point(s) across {len(files)} "
        f"driver module(s) all instrumented; all jit sites tracked"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
