#!/usr/bin/env python
"""Static check: every distributed driver uses the shared instrumentation.

Walks ``spark_rapids_ml_tpu/parallel/distributed_*.py`` and requires that
every module-level public entry point (a ``distributed_*`` function that is
not a ``*_kernel``) carries the ``@fit_instrumentation(...)`` decorator from
``spark_rapids_ml_tpu.obs``. New drivers therefore cannot silently ship
unobserved: tier-1 runs this via ``tests/test_obs_reports.py``.

Pure ``ast`` — no jax import, no package import, so it runs anywhere in
milliseconds. Exit 0 = all instrumented; exit 1 = offenders listed on
stdout, one ``file:line name`` per line.
"""

from __future__ import annotations

import ast
import glob
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PARALLEL_GLOB = os.path.join(
    REPO, "spark_rapids_ml_tpu", "parallel", "distributed_*.py"
)
DECORATOR_NAME = "fit_instrumentation"


def _decorator_names(fn: ast.FunctionDef):
    for dec in fn.decorator_list:
        node = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(node, ast.Attribute):
            yield node.attr
        elif isinstance(node, ast.Name):
            yield node.id


def _is_entry_point(fn: ast.FunctionDef) -> bool:
    return (
        fn.name.startswith("distributed_")
        and not fn.name.endswith("_kernel")
    )


def check_file(path: str):
    """Yield (lineno, name) for every uninstrumented entry point."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if not _is_entry_point(node):
            continue
        if DECORATOR_NAME not in set(_decorator_names(node)):
            yield node.lineno, node.name


def main() -> int:
    files = sorted(glob.glob(PARALLEL_GLOB))
    if not files:
        print("ERROR: no parallel/distributed_*.py files found")
        return 1
    offenders = []
    checked = 0
    for path in files:
        rel = os.path.relpath(path, REPO)
        tree = ast.parse(open(path).read(), filename=path)
        entry_points = [
            n for n in tree.body
            if isinstance(n, ast.FunctionDef) and _is_entry_point(n)
        ]
        checked += len(entry_points)
        for lineno, name in check_file(path):
            offenders.append(f"{rel}:{lineno} {name}")
    if offenders:
        print(
            f"{len(offenders)} distributed driver(s) missing "
            f"@{DECORATOR_NAME}:"
        )
        for line in offenders:
            print(f"  {line}")
        return 1
    print(
        f"OK: {checked} distributed entry point(s) across {len(files)} "
        "driver module(s) all instrumented"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
