"""Round-4 wave-2 chip bench: production-harness block A/B + config-4 rerun.

The committed gram sweep (`records/r04/gram_sweep.json`) ranks block
shapes in a NON-donated harness (`acc = acc + fused_centered_gram(...)`)
where 1024×1024 wins by +17% over the production constants. The
production accumulate is the donated `update_stats_fused` path, which
composes differently (accumulator donation, col_sum fusion), so the
constants only move on evidence from THIS harness: each arm monkeypatches
`pallas_gram._BLOCK_N/_BLOCK_R` (read at call time via
`gram_block_shape()`) and times the real `update_stats_fused`.

Then config 4 (the north-star 10M×4096 bench) re-runs with the winning
shape via the same monkeypatch, emitting `bench_config4_blocks.json` —
committed evidence for flipping the defaults.

Single process, one chip claim, exit 2 if no chip (wrapper retries).
"""

from __future__ import annotations

import contextlib
import datetime
import io
import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "records", "r04")
sys.path.insert(0, REPO)


def stamp() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def log(msg: str) -> None:
    os.makedirs(OUT, exist_ok=True)
    with open(os.path.join(OUT, "status.log"), "a") as f:
        f.write(f"{msg}: {stamp()}\n")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "tpu")
    log("wave2 probe start")
    try:
        import jax

        device = jax.devices()[0]
    except Exception as exc:  # noqa: BLE001
        log(f"wave2 probe FAILED ({type(exc).__name__})")
        return 2
    if device.platform == "cpu":
        log("wave2 probe FAILED (cpu backend)")
        return 2
    log("wave2 probe ok")

    import numpy as np
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops import pallas_gram
    from spark_rapids_ml_tpu.ops.streaming import (
        init_stats,
        update_stats_fused,
    )
    from spark_rapids_ml_tpu.utils.platform import PEAK_FLOPS_BF16

    rows, cols, steps = 65536, 4096, 24
    key = jax.random.PRNGKey(0)
    col_scale = (1.0 + jnp.arange(cols, dtype=jnp.float32)) ** -0.5
    x = jax.device_put(
        jax.random.normal(key, (rows, cols), dtype=jnp.float32)
        * col_scale[None, :], device)
    peak = PEAK_FLOPS_BF16.get(
        str(getattr(device, "device_kind", device.platform)))

    arms = [(512, 1024), (512, 2048), (1024, 1024), (1024, 2048),
            (512, 512)]
    results = []
    base = (pallas_gram._BLOCK_N, pallas_gram._BLOCK_R)
    try:
        for bn, br in arms:
            pallas_gram._BLOCK_N, pallas_gram._BLOCK_R = bn, br
            try:
                stats = init_stats(cols, dtype=jnp.float32, device=device)
                stats = update_stats_fused(stats, x)  # compile
                int(np.asarray(stats.count))
                stats = init_stats(cols, dtype=jnp.float32, device=device)
                t0 = time.perf_counter()
                for _ in range(steps):
                    stats = update_stats_fused(stats, x)
                int(np.asarray(stats.count))  # fence
                rate = steps * rows / (time.perf_counter() - t0)
            except Exception as exc:  # noqa: BLE001 - arm must not kill run
                results.append({"arm": f"donated_{bn}x{br}",
                                "error": f"{type(exc).__name__}: {exc}"[:200]})
                continue
            rec = {
                "metric": f"donated update_stats_fused rows/sec "
                          f"({rows}x{cols}, bfloat16_3x)",
                "arm": f"donated_{bn}x{br}",
                "value": round(rate, 1),
                "unit": "rows/sec",
                "mfu": (round(2.0 * cols * cols * rate / peak, 4)
                        if peak else None),
            }
            results.append(rec)
    finally:
        pallas_gram._BLOCK_N, pallas_gram._BLOCK_R = base

    ok_arms = [r for r in results if "value" in r]
    with open(os.path.join(OUT, "block_ab.json"), "w") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
        if ok_arms:
            best = max(ok_arms, key=lambda r: r["value"])
            f.write(json.dumps({
                "metric": "donated-harness block winner",
                "arm": best["arm"], "value": best["value"],
                "mfu": best["mfu"], "recorded_utc": stamp(),
            }) + "\n")
    log("wave2 block_ab done")

    if ok_arms:
        best = max(ok_arms, key=lambda r: r["value"])
        bn, br = (int(v) for v in
                  best["arm"].removeprefix("donated_").split("x"))
        pallas_gram._BLOCK_N, pallas_gram._BLOCK_R = bn, br
        import bench

        os.environ["BENCH_SKIP_PROBE"] = "1"
        buf = io.StringIO()
        try:
            with contextlib.redirect_stdout(buf):
                bench.main()
        except Exception as exc:  # noqa: BLE001
            with open(os.path.join(OUT, "config4_blocks.err"), "w") as f:
                f.write(f"{type(exc).__name__}: {exc}\n")
                f.write(traceback.format_exc())
            log("wave2 config4 FAILED")
        else:
            text = buf.getvalue()
            # annotate the record with the block shape it ran under
            lines = [ln for ln in text.splitlines() if ln.strip()]
            try:
                rec = json.loads(lines[-1])
                rec["gram_block"] = f"{bn}x{br}"
                rec["recorded_utc"] = stamp()
                lines[-1] = json.dumps(rec)
            except Exception:  # noqa: BLE001 - keep raw text on parse issues
                pass
            with open(os.path.join(OUT, "bench_config4_blocks.json"),
                      "w") as f:
                f.write("\n".join(lines) + "\n")
            log("wave2 config4 ok")

    with open(os.path.join(OUT, "wave2_done"), "w") as f:
        f.write(stamp() + "\n")
    log("wave2 ALL DONE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
