#!/bin/bash
# Wave-3 wrapper: after wave 2, retry the UMAP 200k record.
cd /root/repo || exit 1
export PYTHONPATH=/root/repo:/root/.axon_site
OUT=/root/repo/records/r04
mkdir -p "$OUT"

while [ ! -f "$OUT/wave2_done" ]; do sleep 60; done

for i in $(seq 1 24); do
  echo "wave3 attempt $i start: $(date -u +%Y-%m-%dT%H:%M:%SZ)" >> "$OUT/loop.log"
  python scripts/bench_r04_wave3.py >> "$OUT/loop.log" 2>&1
  rc=$?
  echo "wave3 attempt $i rc=$rc: $(date -u +%Y-%m-%dT%H:%M:%SZ)" >> "$OUT/loop.log"
  [ -f "$OUT/wave3_done" ] && exit 0
  sleep 300
done
