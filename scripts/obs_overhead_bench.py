#!/usr/bin/env python
"""Observability-overhead bench: what does watching cost?

Drives the SAME closed-loop mixed-size serve traffic twice — once with
the history sampler (``obs.tsdb.MetricsSampler`` + the ``obs.devmon``
device collector) OFF, once with it ON at an aggressive cadence — and
emits ONE sentinel-judgeable ``bench_common.emit_record`` line whose
headline metric is the throughput overhead fraction::

    overhead_fraction = max(0, 1 - rows_per_sec_on / rows_per_sec_off)

LOWER is better (explicit ``higher_is_better: false`` — the sentinel
judges an observability cost regression exactly like a perf
regression). The record also carries the sampler's OWN accounting
(``sparkml_obs_overhead_seconds_total`` delta over the ON phase divided
by its wall-clock) so the self-reported cost and the black-box measured
cost can be cross-checked; the acceptance bar for this PR is
``overhead_fraction < 0.02`` at the default 1 s cadence (the bench
defaults to a 10× faster 100 ms cadence to make the cost measurable at
all — pass ``SPARKML_BENCH_OBS_SAMPLE_MS=1000`` for the shipping
configuration).

Phase order is off→on→off→on (two interleaved rounds per arm, means
compared) so drift in the container's background load lands on both
arms instead of biasing whichever phase ran last.

A second experiment reuses the same tape to price the per-model cost
ledger (``obs.accounting.ResourceLedger``) riding the request- and
batch-completion seams: sampler OFF, ledger toggled off→on→off→on, and
a SECOND record (``bench: obs_overhead_accounting``) is emitted whose
``accounting_overhead_fraction`` is judged against the same documented
bar (``SPARKML_BENCH_OBS_ACCT_BAR``, default 0.02). The process exits
non-zero when the ledger arm misses that bar, so CI can gate on it.

A FOURTH experiment prices being a polled fleet peer
(``obs.federation.fleet_export``): sampler ON in both sub-arms (the
export needs real series to walk), with an aggregator-shaped background
thread polling ``fleet_export(cursor)`` at ``SPARKML_BENCH_OBS_FED_MS``
(default 100 ms — far hotter than the 2 s shipping poll cadence)
toggled off→on→off→on. The record (``bench: obs_overhead_federation``)
carries ``federation_overhead_fraction`` judged against
``SPARKML_BENCH_OBS_FED_BAR`` (default 0.02); a miss exits non-zero.

A third experiment prices the fit-path step monitor (``obs.fitmon``):
a tape of repeated PCA fits, each wrapped in ``fitmon.fit_run`` so the
step-monitor call sites execute in BOTH arms, with the monitor toggled
off→on→off→on. A THIRD record (``bench: obs_overhead_fitmon``) carries
``fitmon_overhead_fraction`` judged against ``SPARKML_BENCH_OBS_FITMON_
BAR`` (default 0.02); a miss also exits non-zero.

Knobs (env): SPARKML_BENCH_OBS_REQUESTS (default 384, per phase),
SPARKML_BENCH_OBS_FEATURES (64), SPARKML_BENCH_OBS_K (16),
SPARKML_BENCH_OBS_THREADS (8), SPARKML_BENCH_OBS_MAX_ROWS (512),
SPARKML_BENCH_OBS_SAMPLE_MS (100), SPARKML_BENCH_OBS_ACCT_BAR (0.02),
SPARKML_BENCH_OBS_FITS (24), SPARKML_BENCH_OBS_FITMON_BAR (0.02),
SPARKML_BENCH_OBS_FED_MS (100), SPARKML_BENCH_OBS_FED_BAR (0.02).
"""

from __future__ import annotations

import concurrent.futures
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench_common  # noqa: E402 (scripts/ on path when run directly)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def main() -> int:
    n_requests = _env_int("SPARKML_BENCH_OBS_REQUESTS", 384)
    n_features = _env_int("SPARKML_BENCH_OBS_FEATURES", 64)
    k = _env_int("SPARKML_BENCH_OBS_K", 16)
    n_threads = _env_int("SPARKML_BENCH_OBS_THREADS", 8)
    max_rows = _env_int("SPARKML_BENCH_OBS_MAX_ROWS", 512)
    sample_ms = _env_int("SPARKML_BENCH_OBS_SAMPLE_MS", 100)

    import jax

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.obs import devmon, get_registry, tsdb
    from spark_rapids_ml_tpu.serve import ModelRegistry, ServeEngine

    device = jax.devices()[0]
    rng = np.random.default_rng(7)
    x = rng.normal(size=(4096, n_features))
    model = PCA().setK(k).fit(x)

    registry = ModelRegistry()
    registry.register("bench_pca", model)
    engine = ServeEngine(
        registry, max_batch_rows=max_rows, max_wait_ms=2.0,
        max_queue_depth=4 * n_requests,
    )
    registry.warmup("bench_pca", max_bucket_rows=max_rows)

    # One fixed traffic tape replayed identically per phase: sizes AND
    # offsets precomputed (numpy Generators are not thread-safe, and the
    # seed must reproduce exactly for sentinel comparisons).
    sizes = rng.integers(1, 257, size=n_requests).tolist()
    starts = [int(rng.integers(0, x.shape[0] - n)) for n in sizes]
    total_rows = int(sum(sizes))

    def run_phase() -> float:
        """Replay the tape; returns rows/sec."""
        def one(i: int) -> None:
            n, start = sizes[i], starts[i]
            engine.predict("bench_pca", x[start:start + n])

        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(n_threads) as pool:
            list(pool.map(one, range(n_requests)))
        wall = time.perf_counter() - t0
        return total_rows / wall if wall > 0 else 0.0

    def obs_overhead_total() -> float:
        snap = get_registry().snapshot().get(
            "sparkml_obs_overhead_seconds_total", {"samples": []})
        return sum(s["value"] for s in snap["samples"])

    run_phase()  # untimed warm pass: queues, caches, thread pools

    sampler = tsdb.MetricsSampler(
        tsdb.TimeSeriesStore(), interval_seconds=sample_ms / 1000.0)
    sampler.register_collector(devmon.get_device_monitor().sample)

    # off → on → off → on: background-load drift hits both arms
    off_rates, on_rates = [], []
    self_reported = 0.0
    on_wall = 0.0
    for _round in range(2):
        off_rates.append(run_phase())
        sampler.start()
        overhead_before = obs_overhead_total()
        t_on = time.perf_counter()
        on_rates.append(run_phase())
        on_wall += time.perf_counter() - t_on
        sampler.stop()
        self_reported += obs_overhead_total() - overhead_before

    # ---- accounting arm: what does the cost ledger's meter cost? ----
    # Same tape, sampler OFF, per-model ledger toggled per phase. The
    # ledger rides the request-completion (note_request) and
    # batch-completion (note_batch_seconds) seams, so this prices
    # exactly the hot-path toll tiering/autoscaling pay for their
    # numbers. The `enabled` flip is honored at the top of every hot
    # method, so the singleton held by the engine/batchers obeys it.
    from spark_rapids_ml_tpu.obs import accounting

    acct_bar = float(
        os.environ.get("SPARKML_BENCH_OBS_ACCT_BAR", "0.02"))
    ledger = accounting.get_ledger()

    def ledger_mutations_total() -> float:
        snap = get_registry().snapshot().get(
            "sparkml_model_ledger_mutations_total", {"samples": []})
        return sum(s["value"] for s in snap["samples"])

    acct_off_rates, acct_on_rates = [], []
    mutations_before = ledger_mutations_total()
    for _round in range(2):
        ledger.enabled = False
        acct_off_rates.append(run_phase())
        ledger.enabled = True
        acct_on_rates.append(run_phase())
    ledger_mutations = ledger_mutations_total() - mutations_before

    # ---- federation arm: what does being a polled fleet peer cost? ----
    # Same tape, but the toggle is an aggregator-shaped export poller: a
    # background thread calling obs.federation.fleet_export(cursor) at
    # SPARKML_BENCH_OBS_FED_MS cadence against the live sampler store
    # (the sampler runs in BOTH sub-arms so the export has real series
    # to walk — the fraction prices only the peer-side export toll, not
    # the sampler it rides on). Cursor advances between polls exactly
    # like FleetAggregator's, so steady-state polls ship small deltas.
    from spark_rapids_ml_tpu.obs import federation

    fed_bar = float(os.environ.get("SPARKML_BENCH_OBS_FED_BAR", "0.02"))
    fed_ms = _env_int("SPARKML_BENCH_OBS_FED_MS", 100)
    sampler.start()
    fed_stop = threading.Event()
    fed_stats = {"polls": 0, "points": 0}

    def fed_poller() -> None:
        cursor = 0.0
        while not fed_stop.wait(fed_ms / 1000.0):
            try:
                doc = federation.fleet_export(
                    cursor, store=sampler.store, engine=engine)
            except Exception:  # noqa: BLE001 - poller must not die mid-arm
                continue
            cursor = float(doc.get("cursor", cursor))
            fed_stats["polls"] += 1
            fed_stats["points"] += sum(
                len(s["points"]) for s in doc.get("series", ()))

    fed_off_rates, fed_on_rates = [], []
    for _round in range(2):
        fed_off_rates.append(run_phase())
        fed_stop.clear()
        fed_thread = threading.Thread(
            target=fed_poller, name="bench-fed-poller", daemon=True)
        fed_thread.start()
        fed_on_rates.append(run_phase())
        fed_stop.set()
        fed_thread.join(timeout=5.0)
    sampler.stop()
    engine.shutdown()

    rows_per_sec_off = float(np.mean(off_rates))
    rows_per_sec_on = float(np.mean(on_rates))
    overhead_fraction = max(
        0.0, 1.0 - rows_per_sec_on / rows_per_sec_off
    ) if rows_per_sec_off > 0 else 0.0

    bench_common.emit_record({
        "bench": "obs_overhead",
        "metric": "obs_overhead_fraction",
        "value": overhead_fraction,
        "unit": "fraction of serve throughput lost to the sampler",
        "higher_is_better": False,
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "requests_per_phase": n_requests,
        "threads": n_threads,
        "rows_per_phase": total_rows,
        "sample_interval_ms": sample_ms,
        "rows_per_sec_off": rows_per_sec_off,
        "rows_per_sec_on": rows_per_sec_on,
        "rows_per_sec_off_rounds": off_rates,
        "rows_per_sec_on_rounds": on_rates,
        "sampler_sweeps": sampler.sweeps,
        "history_series": sampler.store.series_count(),
        "self_reported_overhead_seconds": self_reported,
        "self_reported_overhead_fraction": (
            self_reported / on_wall if on_wall > 0 else 0.0
        ),
    })

    acct_off = float(np.mean(acct_off_rates))
    acct_on = float(np.mean(acct_on_rates))
    accounting_overhead = max(
        0.0, 1.0 - acct_on / acct_off
    ) if acct_off > 0 else 0.0
    gate_ok = accounting_overhead <= acct_bar
    bench_common.emit_record({
        "bench": "obs_overhead_accounting",
        "metric": "accounting_overhead_fraction",
        "value": accounting_overhead,
        "unit": "fraction of serve throughput lost to the cost ledger",
        "higher_is_better": False,
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "requests_per_phase": n_requests,
        "threads": n_threads,
        "rows_per_phase": total_rows,
        "rows_per_sec_off": acct_off,
        "rows_per_sec_on": acct_on,
        "rows_per_sec_off_rounds": acct_off_rates,
        "rows_per_sec_on_rounds": acct_on_rates,
        "ledger_mutations_during_on_phases": ledger_mutations,
        "gate_bar": acct_bar,
        "gate_ok": gate_ok,
    }, include_metrics=False)

    fed_off = float(np.mean(fed_off_rates))
    fed_on = float(np.mean(fed_on_rates))
    federation_overhead = max(
        0.0, 1.0 - fed_on / fed_off
    ) if fed_off > 0 else 0.0
    fed_ok = federation_overhead <= fed_bar
    bench_common.emit_record({
        "bench": "obs_overhead_federation",
        "metric": "federation_overhead_fraction",
        "value": federation_overhead,
        "unit": "fraction of serve throughput lost to fleet export polls",
        "higher_is_better": False,
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "requests_per_phase": n_requests,
        "threads": n_threads,
        "rows_per_phase": total_rows,
        "poll_interval_ms": fed_ms,
        "sample_interval_ms": sample_ms,
        "rows_per_sec_off": fed_off,
        "rows_per_sec_on": fed_on,
        "rows_per_sec_off_rounds": fed_off_rates,
        "rows_per_sec_on_rounds": fed_on_rates,
        "export_polls": fed_stats["polls"],
        "export_points_shipped": fed_stats["points"],
        "gate_bar": fed_bar,
        "gate_ok": fed_ok,
    }, include_metrics=False)

    # ---- fitmon arm: what does the fit-path step monitor cost? ----
    # Same toggling discipline, but the tape is repeated FITS: the
    # step-monitor call sites (fit_run + current_run().step inside the
    # distributed drivers) execute in BOTH arms — OFF prices the
    # disabled null-run path, ON prices real step recording — so the
    # fraction is exactly the seam's toll, not fit-vs-serve drift.
    from spark_rapids_ml_tpu.obs import fitmon

    fitmon_bar = float(
        os.environ.get("SPARKML_BENCH_OBS_FITMON_BAR", "0.02"))
    n_fits = _env_int("SPARKML_BENCH_OBS_FITS", 24)
    monitor = fitmon.get_fit_monitor()
    x_fit = x[:1024]
    fit_rows_per_phase = n_fits * x_fit.shape[0]

    def run_fit_phase() -> float:
        """Replay the fit tape; returns rows/sec."""
        t0 = time.perf_counter()
        for _ in range(n_fits):
            with fitmon.fit_run("bench_fitmon"):
                PCA().setK(k).fit(x_fit)
        wall = time.perf_counter() - t0
        return fit_rows_per_phase / wall if wall > 0 else 0.0

    saved_enabled = monitor.enabled
    monitor.enabled = True
    run_fit_phase()  # untimed warm pass: compile cache for the fit shape
    fit_off_rates, fit_on_rates = [], []
    for _round in range(2):
        monitor.enabled = False
        fit_off_rates.append(run_fit_phase())
        monitor.enabled = True
        fit_on_rates.append(run_fit_phase())
    monitor.enabled = saved_enabled

    fit_off = float(np.mean(fit_off_rates))
    fit_on = float(np.mean(fit_on_rates))
    fitmon_overhead = max(
        0.0, 1.0 - fit_on / fit_off
    ) if fit_off > 0 else 0.0
    fitmon_ok = fitmon_overhead <= fitmon_bar
    bench_common.emit_record({
        "bench": "obs_overhead_fitmon",
        "metric": "fitmon_overhead_fraction",
        "value": fitmon_overhead,
        "unit": "fraction of fit throughput lost to the step monitor",
        "higher_is_better": False,
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "fits_per_phase": n_fits,
        "rows_per_phase": fit_rows_per_phase,
        "rows_per_sec_off": fit_off,
        "rows_per_sec_on": fit_on,
        "rows_per_sec_off_rounds": fit_off_rates,
        "rows_per_sec_on_rounds": fit_on_rates,
        "monitored_runs": len(monitor.recent_runs()),
        "gate_bar": fitmon_bar,
        "gate_ok": fitmon_ok,
    }, include_metrics=False)

    failed = False
    if not gate_ok:
        bench_common.log(
            f"accounting overhead {accounting_overhead:.4f} exceeds "
            f"bar {acct_bar:.4f}")
        failed = True
    if not fitmon_ok:
        bench_common.log(
            f"fitmon overhead {fitmon_overhead:.4f} exceeds "
            f"bar {fitmon_bar:.4f}")
        failed = True
    if not fed_ok:
        bench_common.log(
            f"federation overhead {federation_overhead:.4f} exceeds "
            f"bar {fed_bar:.4f}")
        failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
