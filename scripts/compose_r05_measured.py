"""Compose BENCH_MEASURED_r05.json from whatever wave records landed.

Reads the round-4 wave outputs (``records/r04``) and the round-5 wave-5
outputs (``records/r05``), picks the best config-4 headline (a wave-2
rerun if one landed, else the committed round-4 headline carried
forward as stale), and bundles every fresh family/precision record —
so ``bench.py``'s CPU-fallback line embeds the newest committed chip
evidence even if no human is around when the window opens. Wave-5's
wrapper runs this after its done marker; it is also safe to run by
hand at harvest time. Never raises past main(); an empty harvest
writes nothing.
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R04 = os.path.join(REPO, "records", "r04")
R05 = os.path.join(REPO, "records", "r05")


def _json_lines(path):
    rows = []
    if not os.path.exists(path):
        return rows
    for line in open(path):
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return rows


def main() -> None:
    headline = None
    # wave-2's config-4 rerun (winner block shape), if it landed
    for name in ("bench_config4_blocks.json",):
        rows = [r for r in _json_lines(os.path.join(R04, name))
                if r.get("platform") == "tpu"]
        if rows:
            headline = rows[-1]
            headline["source_file"] = f"records/r04/{name}"
            break
    if headline is None:
        # carry the committed round-4 headline forward
        prior = os.path.join(REPO, "BENCH_MEASURED_r04.json")
        try:
            with open(prior) as f:
                headline = json.load(f).get("headline")
        except Exception:  # noqa: BLE001 - carry nothing, keep sections
            headline = None

    sections = {}
    for rel, key in (
        (os.path.join(R04, "bench_families.json"), "families_r04"),
        (os.path.join(R04, "block_ab.json"), "block_ab"),
        (os.path.join(R04, "bench_models_batched.json"),
         "models_batched"),
        (os.path.join(R04, "scale_umap.json"), "umap_scale"),
        (os.path.join(R04, "bench_config3_clean.json"), "config3_clean"),
        (os.path.join(R05, "bench_models_wide.json"), "models_wide"),
        (os.path.join(R05, "bench_gbt.json"), "gbt"),
        (os.path.join(R05, "gram_precision.json"), "gram_precision"),
    ):
        rows = _json_lines(rel)
        if rows:
            sections[key] = rows

    if headline is None and not sections:
        print("compose_r05: nothing landed yet; not writing")
        return

    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, cwd=REPO,
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 - metadata only
        commit = "unknown"
    out = {
        "composed_utc": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
        "commit": commit,
        "headline": headline,
        **sections,
    }
    path = os.path.join(REPO, "BENCH_MEASURED_r05.json")
    try:
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    except Exception as exc:  # noqa: BLE001 - report, never crash a wave
        print(f"compose_r05: write failed: {exc}")
        return
    print(f"compose_r05: wrote {path} "
          f"(headline={'fresh' if headline and headline.get('source_file') else 'carried'}, "
          f"sections={sorted(sections)})")


if __name__ == "__main__":
    main()
