#!/usr/bin/env python
"""Serving-engine bench: throughput, tail latency, batch occupancy.

Registers a fitted PCA model, warms its shape buckets, then drives a
fixed count of mixed-size predict requests through the engine from a
thread pool — the closed-loop analogue of real ragged traffic — and
emits ONE ``bench_common.emit_record`` JSON line so
``scripts/perf_sentinel.py`` can judge serving regressions against the
committed history from the next PR onward:

* ``rows_per_sec``            — end-to-end serving throughput;
* ``p50`` / ``p95`` / ``p99`` — request latency seconds (also under
  ``percentiles``, the sentinel's per-percentile judging shape), plus
  ``p99_ms`` (the same tail in milliseconds — the headline number the
  pipeline PR is judged on);
* ``p99_cold`` / ``p99_steady`` — the cold-vs-steady split: tail latency
  over the first ~10% of requests (first-touch compiles, cache warming,
  pipeline fill) vs the steady-state remainder — a warmup regression
  and a hot-path regression stop hiding behind one blended number;
* ``mean_batch_occupancy``    — real rows / bucket rows over the run
  (how well coalescing fills the padded shapes);
* ``pipeline_overlap_fraction`` — union device-busy time ÷ wall time
  (``sparkml_serve_device_busy_seconds_total`` over the run): how much
  of the bench wall-clock had at least one batch in flight. > 0
  whenever the pipelined batcher ran; the deeper companion
  ``pipeline_overlap2_fraction`` (>= 2 batches in flight) is the
  stage/compute overlap the PIPELINE_DEPTH=2 window buys;
* ``pipeline_depth``          — the engine's in-flight window, so a
  history line is attributable to its pipeline posture;
* ``recompile_count``         — distinct-signature compiles during the
  serve phase; steady state must stay at 0 (warmup owns them all);
* ``slo_fast_burn_rate``      — the worst fast-window (5 m) SLO burn rate
  at the end of the run (``obs.slo``; > 14.4 would page);
* ``slo_budget_remaining``    — the worst remaining error-budget fraction
  across the engine's SLOs. The sentinel judges this one
  HIGHER-is-better despite the fraction unit (see
  ``perf_sentinel.higher_is_better``).

Scenarios (``SPARKML_BENCH_SERVE_SCENARIO``):

* ``engine`` (default) — the single-model engine bench above, judged
  against the committed ``records/bench_serve_r09.json`` lineage;
* ``pipeline`` — staged-vs-FUSED whole-pipeline serving: one fitted
  scaler → PCA → logreg ``PipelineModel`` served twice through
  identical closed-loop traffic — once at ``pipeline_depth=1`` (the
  staged blocking per-stage loop, one host round trip per stage) and
  once through the fused one-XLA-program path — emitting
  ``metric="fused_p99_ms"`` (explicit lower-is-better) with
  ``staged_p99_ms`` and the speedup alongside;
* ``wire`` — JSON-vs-binary wire format over the REAL HTTP server: the
  same rows sent both ways, parse-phase latency read back from the
  ``sparkml_serve_parse_seconds{format}`` sketch ``serve.wire``'s
  decoders feed — emitting ``metric="wire_parse_ms_p99"`` (the binary
  parse tail, explicit lower-is-better) with ``json_parse_ms_p99`` and
  the parse speedup alongside;
* ``multidevice`` — the replicated serving tier's scaling proof: the
  same closed-loop engine bench run in SUBPROCESSES at forced host
  device counts 1 / 2 / 4 (``XLA_FLAGS=
  --xla_force_host_platform_device_count=N`` — device count is fixed at
  jax init, so each count needs its own process), emitting
  ``rows_per_sec`` per count and ``metric="serve_multidevice_scaling_
  efficiency"`` = (rows/sec at N ÷ rows/sec at 1) ÷ N (explicit
  higher-is-better). **CPU-CI honesty**: a single-core container
  cannot exhibit real FLOPS parallelism across virtual host devices,
  so the scenario models a fixed per-batch device service time
  (``SPARKML_BENCH_SERVE_DEVICE_MS``, default 60 — injected as a
  ``latency`` fault at every replica dispatch, a GIL-released sleep)
  and therefore judges the TIER: can placement + per-replica
  batchers/staging-pools keep N devices concurrently busy? On real
  multi-chip hardware set ``SPARKML_BENCH_SERVE_DEVICE_MS=0`` to
  measure true compute scaling. The modeled service time is stamped
  into the record so a baseline can never silently mix the two modes.

Knobs (env): SPARKML_BENCH_SERVE_REQUESTS (default 512),
SPARKML_BENCH_SERVE_FEATURES (64), SPARKML_BENCH_SERVE_K (16),
SPARKML_BENCH_SERVE_THREADS (8), SPARKML_BENCH_SERVE_MAX_ROWS (512),
plus the engine's SPARK_RAPIDS_ML_TPU_SERVE_{PIPELINE_DEPTH,PRECISION}.
"""

from __future__ import annotations

import concurrent.futures
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench_common  # noqa: E402 (scripts/ on path when run directly)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _closed_loop(predict, n_requests: int, n_threads: int):
    """Drive ``predict(i)`` from a thread pool; returns the per-request
    latency array and the wall time."""
    latencies = np.zeros(n_requests)

    def one(i: int) -> None:
        t0 = time.perf_counter()
        predict(i)
        latencies[i] = time.perf_counter() - t0

    t_run = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(n_threads) as pool:
        list(pool.map(one, range(n_requests)))
    return latencies, time.perf_counter() - t_run


def _fit_pipeline(rng, n_features: int, k: int):
    """One fitted scaler → PCA → binary-logreg PipelineModel plus its
    training matrix — the fused-serving specimen."""
    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.data.frame import VectorFrame
    from spark_rapids_ml_tpu.models.logistic_regression import (
        LogisticRegression,
    )
    from spark_rapids_ml_tpu.models.pipeline import Pipeline
    from spark_rapids_ml_tpu.models.scaler import StandardScaler

    x = rng.normal(size=(4096, n_features))
    y = (x[:, 0] + 0.25 * x[:, 1] > 0).astype(float)
    frame = VectorFrame({"features": x, "label": list(y)})
    pipeline = Pipeline(stages=[
        StandardScaler().setWithMean(True).setOutputCol("scaled"),
        PCA().setK(k).setInputCol("scaled").setOutputCol("reduced"),
        LogisticRegression().setInputCol("reduced").setLabelCol("label"),
    ])
    return pipeline.fit(frame), x


def scenario_pipeline(device) -> int:
    """Staged-vs-fused whole-pipeline serving, closed loop, same
    traffic — the Flare-transplant headline number."""
    n_requests = _env_int("SPARKML_BENCH_SERVE_REQUESTS", 512)
    n_features = _env_int("SPARKML_BENCH_SERVE_FEATURES", 64)
    k = _env_int("SPARKML_BENCH_SERVE_K", 16)
    n_threads = _env_int("SPARKML_BENCH_SERVE_THREADS", 8)
    max_rows = _env_int("SPARKML_BENCH_SERVE_MAX_ROWS", 512)

    from spark_rapids_ml_tpu.serve import ModelRegistry, ServeEngine

    rng = np.random.default_rng(7)
    model, x = _fit_pipeline(rng, n_features, k)
    sizes = rng.integers(1, 257, size=n_requests).tolist()
    starts = [int(rng.integers(0, x.shape[0] - n)) for n in sizes]

    results = {}
    # depths explicit on BOTH arms: the fused arm must not inherit a
    # SPARK_RAPIDS_ML_TPU_SERVE_PIPELINE_DEPTH=1 kill switch from the
    # environment and silently measure the staged loop twice
    for mode, depth in (("staged", 1), ("fused", 2)):
        registry = ModelRegistry()
        registry.register("bench_pipeline", model)
        engine = ServeEngine(
            registry, max_batch_rows=max_rows, max_wait_ms=2.0,
            max_queue_depth=4 * n_requests, pipeline_depth=depth,
        )
        # depth=1 at native precision never builds the fused program —
        # the staged mode IS the blocking per-stage transform loop
        engine.warmup("bench_pipeline")
        latencies, wall = _closed_loop(
            lambda i: engine.predict(
                "bench_pipeline", x[starts[i]:starts[i] + sizes[i]]),
            n_requests, n_threads)
        engine.shutdown()
        results[mode] = {
            "p50": float(np.percentile(latencies, 50)),
            "p99": float(np.percentile(latencies, 99)),
            "wall": wall,
            "rows_per_sec": sum(sizes) / wall if wall > 0 else 0.0,
        }
    fused_p99_ms = results["fused"]["p99"] * 1000.0
    staged_p99_ms = results["staged"]["p99"] * 1000.0
    bench_common.emit_record({
        "bench": "serve_pipeline_fused",
        "metric": "fused_p99_ms",
        "value": fused_p99_ms,
        "unit": "ms (p99 fused whole-pipeline request latency)",
        "higher_is_better": False,
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "requests": n_requests,
        "threads": n_threads,
        "stages": 3,
        "fused_p99_ms": fused_p99_ms,
        "staged_p99_ms": staged_p99_ms,
        "fused_p50_ms": results["fused"]["p50"] * 1000.0,
        "staged_p50_ms": results["staged"]["p50"] * 1000.0,
        "fused_rows_per_sec": results["fused"]["rows_per_sec"],
        "staged_rows_per_sec": results["staged"]["rows_per_sec"],
        "fused_speedup_p99": (staged_p99_ms / fused_p99_ms
                              if fused_p99_ms > 0 else 0.0),
    }, include_metrics=False)
    return 0


def scenario_wire(device) -> int:
    """JSON-vs-binary wire parse over the real HTTP server: identical
    rows both ways, verdict read from the decoders' own parse-latency
    sketch (the measured, not asserted, protocol cost)."""
    import http.client
    import json

    # More observations than the engine bench: the binary parse is tens
    # of µs, so its p99 estimate needs a deep sample to sit above the
    # OS-scheduler spike noise instead of IN it.
    n_requests = _env_int("SPARKML_BENCH_SERVE_REQUESTS", 1024)
    n_features = _env_int("SPARKML_BENCH_SERVE_FEATURES", 64)
    k = _env_int("SPARKML_BENCH_SERVE_K", 16)
    max_rows = _env_int("SPARKML_BENCH_SERVE_MAX_ROWS", 512)
    rows_per_request = _env_int("SPARKML_BENCH_SERVE_WIRE_ROWS", 256)

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.serve import ModelRegistry, ServeEngine
    from spark_rapids_ml_tpu.serve import wire
    from spark_rapids_ml_tpu.serve.server import start_serve_server

    rng = np.random.default_rng(7)
    x = rng.normal(size=(4096, n_features))
    model = PCA().setK(k).fit(x)
    registry = ModelRegistry()
    registry.register("bench_pca", model)
    engine = ServeEngine(registry, max_batch_rows=max_rows,
                         max_wait_ms=2.0,
                         max_queue_depth=4 * n_requests)
    engine.warmup("bench_pca")
    server = start_serve_server(engine)
    port = server.server_address[1]

    starts = [int(rng.integers(0, x.shape[0] - rows_per_request))
              for _ in range(n_requests)]
    e2e = {}
    try:
        for fmt in ("json", "binary"):
            conn = http.client.HTTPConnection("127.0.0.1", port)
            lat = np.zeros(n_requests)
            for i, start in enumerate(starts):
                batch = x[start:start + rows_per_request]
                if fmt == "json":
                    body = json.dumps({"model": "bench_pca",
                                       "rows": batch.tolist()})
                    headers = {"Content-Type": "application/json"}
                else:
                    body = wire.encode_request("bench_pca", batch,
                                               dtype=np.float64)
                    headers = {"Content-Type": wire.BINARY_CONTENT_TYPE}
                t0 = time.perf_counter()
                conn.request("POST", "/predict", body, headers)
                resp = conn.getresponse()
                resp.read()
                lat[i] = time.perf_counter() - t0
                if resp.status != 200:
                    raise RuntimeError(
                        f"{fmt} request {i} failed: {resp.status}")
            conn.close()
            e2e[fmt] = {"p50": float(np.percentile(lat, 50)),
                        "p99": float(np.percentile(lat, 99))}
    finally:
        server.shutdown()
        engine.shutdown()
    json_q = wire.parse_quantiles("json")
    bin_q = wire.parse_quantiles("binary")
    json_p99_ms = (json_q.get("p99") or 0.0) * 1000.0
    bin_p99_ms = (bin_q.get("p99") or 0.0) * 1000.0
    bench_common.emit_record({
        "bench": "serve_wire_format",
        "metric": "wire_parse_ms_p99",
        "value": bin_p99_ms,
        "unit": "ms (p99 binary request-body parse latency)",
        "higher_is_better": False,
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "requests": n_requests,
        "rows_per_request": rows_per_request,
        "wire_parse_ms_p99": bin_p99_ms,
        "json_parse_ms_p99": json_p99_ms,
        "wire_parse_ms_p50": (bin_q.get("p50") or 0.0) * 1000.0,
        "json_parse_ms_p50": (json_q.get("p50") or 0.0) * 1000.0,
        "parse_speedup_p99": (json_p99_ms / bin_p99_ms
                              if bin_p99_ms > 0 else 0.0),
        "json_e2e_p99_ms": e2e["json"]["p99"] * 1000.0,
        "binary_e2e_p99_ms": e2e["binary"]["p99"] * 1000.0,
    }, include_metrics=False)
    return 0


CHILD_RESULT_PREFIX = "MULTIDEVICE_CHILD_RESULT "
COLDSTART_CHILD_PREFIX = "COLDSTART_CHILD_RESULT "


def scenario_coalesce() -> int:
    """Load-aware coalescing concentration, the PR 13 ROADMAP item: at
    4 replicas, spreading SMALL requests least-loaded across every
    queue thinned batches to ~1.6 requests/batch (vs ~4 at 1 replica).
    This scenario runs the SAME small-request closed loop twice in
    4-device subprocesses — concentration ON (the new default: the
    small-request tier routes to the lowest-index lightly-loaded
    replica, spilling as depth grows) vs OFF (pure least-loaded) — and
    emits ``metric="serve_coalesce_density_ratio"`` = requests/batch ON
    ÷ OFF (explicit higher-is-better). Gate (rc=1): the ratio must
    clear ``SPARKML_BENCH_COALESCE_MIN`` (default 1.3)."""
    import subprocess

    min_ratio = float(os.environ.get("SPARKML_BENCH_COALESCE_MIN",
                                     "1.3"))
    results = {}
    for mode, flag in (("concentrated", "1"), ("spread", "0")):
        env = dict(os.environ)
        env["SPARKML_BENCH_SERVE_SCENARIO"] = "_multidevice_child"
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
        env["XLA_FLAGS"] = bench_common.force_device_count_flags(4)
        env.pop("SPARK_RAPIDS_ML_TPU_SERVE_REPLICAS", None)
        env["SPARK_RAPIDS_ML_TPU_SERVE_CONCENTRATE"] = flag
        # the small-request tier under LIGHT load: quarter-bucket
        # requests from few threads — the regime the PR 13 bench showed
        # thinning batches across N replica queues
        env.setdefault("SPARKML_BENCH_SERVE_MD_ROWS", "64")
        env.setdefault("SPARKML_BENCH_SERVE_MD_REQUESTS", "192")
        env.setdefault("SPARKML_BENCH_SERVE_THREADS", "4")
        env.setdefault("SPARKML_BENCH_SERVE_DEVICE_MS", "15")
        bench_common.log(f"bench_serve coalesce: {mode} child at "
                         f"4 device(s)")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=900,
        )
        result = bench_common.prefixed_result(proc.stdout,
                                              CHILD_RESULT_PREFIX)
        if proc.returncode != 0 or result is None:
            bench_common.log(
                f"coalesce {mode} child FAILED "
                f"(rc={proc.returncode}): {proc.stderr[-2000:]}")
            return 1
        results[mode] = result
    on = results["concentrated"]
    off = results["spread"]
    ratio = (on["requests_per_batch"] / off["requests_per_batch"]
             if off["requests_per_batch"] else 0.0)
    bench_common.emit_record({
        "bench": "serve_coalesce",
        "metric": "serve_coalesce_density_ratio",
        "value": ratio,
        "unit": ("requests/batch with small-request concentration ON "
                 "over OFF at 4 replicas under light load"),
        "higher_is_better": True,
        "platform": on["platform"],
        "device_kind": on["device_kind"],
        "requests": on["requests"],
        "rows_per_request": on["rows_per_request"],
        "threads": on["threads"],
        "density_concentrated": on["requests_per_batch"],
        "density_spread": off["requests_per_batch"],
        "batches_concentrated": on["batches"],
        "batches_spread": off["batches"],
        "rows_per_sec_concentrated": on["rows_per_sec"],
        "rows_per_sec_spread": off["rows_per_sec"],
        "p99_ms_concentrated": on["p99_ms"],
        "p99_ms_spread": off["p99_ms"],
        "replica_split_concentrated": on["replica_split"],
        "replica_split_spread": off["replica_split"],
    }, include_metrics=False)
    bench_common.log(
        f"bench_serve coalesce: {on['requests_per_batch']:.2f} req/"
        f"batch concentrated vs {off['requests_per_batch']:.2f} spread "
        f"({ratio:.2f}x)")
    if ratio < min_ratio:
        bench_common.log(
            f"bench_serve coalesce FAIL: density ratio {ratio:.2f} < "
            f"{min_ratio}")
        return 1
    return 0


def scenario_coldstart() -> int:
    """The zero-cold-start proof: warm-restart vs cold-compile, each in
    its own subprocess (a REAL process restart — in-memory jit caches
    cannot leak across).

    A prepare child fits + saves a PCA model, registers it in a
    manifest-backed registry, and warms the full bucket ladder with the
    persistent executable cache enabled (populating both the warm
    manifest and the cache). Then two restart children each recover the
    registry from the manifest, rebuild the engine, replay the warm
    manifest (``engine.warm_from_manifest``) and serve a first request:

    * the **cold** arm runs with the cache DISABLED — every ladder step
      pays a fresh XLA lower+compile (what every restart cost before
      this tier);
    * the **warm** arm runs with the cache on — every ladder step loads
      its executable from disk, and the child asserts ZERO fresh
      compiles via ``obs.xprof.signature_count`` accounting.

    Emits ``metric="serve_cold_start_ms"`` (the warm arm, explicit
    lower-is-better) with the cold arm and the speedup alongside.
    Gates (rc=1): the warm arm must show zero fresh compiles and be at
    least ``SPARKML_BENCH_COLDSTART_MIN_RATIO`` (default 10) times
    faster than the cold arm."""
    import json
    import subprocess
    import tempfile

    min_ratio = float(os.environ.get(
        "SPARKML_BENCH_COLDSTART_MIN_RATIO", "10"))
    workdir = tempfile.mkdtemp(prefix="sparkml_coldstart_")
    cache_dir = os.path.join(workdir, "aot_cache")
    manifest = os.path.join(workdir, "manifest.json")

    def _child(mode: str, cached: bool):
        env = dict(os.environ)
        env["SPARKML_BENCH_SERVE_SCENARIO"] = "_coldstart_child"
        env["SPARKML_BENCH_COLDSTART_MODE"] = mode
        env["SPARKML_BENCH_COLDSTART_DIR"] = workdir
        env["SPARK_RAPIDS_ML_TPU_SERVE_MANIFEST"] = manifest
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
        # a production-shaped bucket ladder (the finer steps the PR 9+
        # pipeline tier actually serves with) — the restart tax scales
        # with ladder size, which is exactly what the cache amortizes
        env.setdefault(
            "SPARK_RAPIDS_ML_TPU_SERVE_BUCKETS",
            "8,16,24,32,48,64,96,128,192,256,384,512,768,1024")
        if cached:
            env["SPARK_RAPIDS_ML_TPU_SERVE_CACHE_DIR"] = cache_dir
        else:
            env.pop("SPARK_RAPIDS_ML_TPU_SERVE_CACHE_DIR", None)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=600,
        )
        result = bench_common.prefixed_result(proc.stdout,
                                              COLDSTART_CHILD_PREFIX)
        if proc.returncode != 0 or result is None:
            bench_common.log(
                f"coldstart {mode} child FAILED "
                f"(rc={proc.returncode}): {proc.stderr[-2000:]}")
            return None
        return result

    bench_common.log("bench_serve coldstart: prepare (fit + warm + "
                     "populate cache)")
    prepared = _child("prepare", cached=True)
    if prepared is None:
        return 1
    bench_common.log("bench_serve coldstart: cold-compile restart arm")
    cold = _child("restart", cached=False)
    if cold is None:
        return 1
    bench_common.log("bench_serve coldstart: warm-restart arm")
    warm = _child("restart", cached=True)
    if warm is None:
        return 1
    speedup = (cold["cold_start_ms"] / warm["cold_start_ms"]
               if warm["cold_start_ms"] > 0 else 0.0)
    record = {
        "bench": "serve_coldstart",
        "metric": "serve_cold_start_ms",
        "value": warm["cold_start_ms"],
        "unit": ("ms from registry recovery to first served request "
                 "on a warm restart (persisted executable cache)"),
        "higher_is_better": False,
        "platform": warm["platform"],
        "device_kind": warm["device_kind"],
        "serve_cold_start_ms": warm["cold_start_ms"],
        "cold_compile_ms": cold["cold_start_ms"],
        "coldstart_speedup": speedup,
        "warm_fresh_compiles": warm["fresh_compiles"],
        "cold_fresh_compiles": cold["fresh_compiles"],
        "warm_first_request_ms": warm["first_request_ms"],
        "cold_first_request_ms": cold["first_request_ms"],
        "manifest_recovery_ms": warm.get("recovery_ms"),
        "warmed_buckets": warm["warmed_buckets"],
        "cache_entries": warm.get("cache_entries"),
        "cache_hits": warm.get("cache_hits"),
        "features": warm["features"],
        "k": warm["k"],
    }
    bench_common.emit_record(record, include_metrics=False)
    bench_common.log(
        f"bench_serve coldstart: warm {warm['cold_start_ms']:.0f} ms vs "
        f"cold {cold['cold_start_ms']:.0f} ms ({speedup:.1f}x), warm "
        f"fresh compiles {warm['fresh_compiles']}")
    failures = []
    if warm["fresh_compiles"] != 0:
        failures.append(
            f"warm restart paid {warm['fresh_compiles']} fresh XLA "
            "compile(s) — the cache missed")
    if speedup < min_ratio:
        failures.append(
            f"warm restart only {speedup:.1f}x faster than cold "
            f"compile < {min_ratio}x")
    if failures:
        bench_common.log("bench_serve coldstart FAIL: "
                         + "; ".join(failures))
        return 1
    return 0


def scenario_coldstart_child(device) -> int:
    """One cold-start arm (own process — see ``scenario_coldstart``).

    ``prepare`` fits + saves + registers + warms (populating the warm
    manifest and, when configured, the executable cache). ``restart``
    measures the restart path: manifest recovery → engine →
    ``warm_from_manifest`` → first request, reporting the total ms and
    the number of fresh XLA compiles the restart paid."""
    import json

    import jax.numpy as jnp

    from spark_rapids_ml_tpu.obs import compile_stats
    from spark_rapids_ml_tpu.obs.aotcache import get_executable_cache
    from spark_rapids_ml_tpu.serve import ModelRegistry, ServeEngine

    mode = os.environ.get("SPARKML_BENCH_COLDSTART_MODE", "prepare")
    workdir = os.environ["SPARKML_BENCH_COLDSTART_DIR"]
    # a REALISTIC deploy shape: the fused scaler → PCA → logreg pipeline
    # (one fused XLA program per bucket plus the three per-stage sync
    # kernels) — the ladder whose recompile cost is the actual restart
    # tax this tier removes
    n_features = _env_int("SPARKML_BENCH_SERVE_FEATURES", 512)
    k = _env_int("SPARKML_BENCH_SERVE_K", 128)
    max_rows = _env_int("SPARKML_BENCH_SERVE_MAX_ROWS", 1024)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(2048, n_features))
    model_path = os.path.join(workdir, "coldstart_pipeline")

    def _fresh_compiles() -> int:
        return sum(s["compiles"] for s in compile_stats().values())

    if mode == "prepare":
        from spark_rapids_ml_tpu import PCA
        from spark_rapids_ml_tpu.data.frame import VectorFrame
        from spark_rapids_ml_tpu.models.feature_scalers import (
            MaxAbsScaler,
            Normalizer,
        )
        from spark_rapids_ml_tpu.models.logistic_regression import (
            LogisticRegression,
        )
        from spark_rapids_ml_tpu.models.pipeline import Pipeline
        from spark_rapids_ml_tpu.models.scaler import StandardScaler

        # a five-stage fused chain: the deeper the pipeline, the bigger
        # the per-bucket XLA program — exactly the restart tax profile
        # of a production deploy
        y = (x[:, 0] + 0.25 * x[:, 1] > 0).astype(float)
        frame = VectorFrame({"features": x, "label": list(y)})
        model = Pipeline(stages=[
            StandardScaler().setWithMean(True).setOutputCol("s1"),
            MaxAbsScaler().setInputCol("s1").setOutputCol("s2"),
            Normalizer().setInputCol("s2").setOutputCol("s3"),
            PCA().setK(k).setInputCol("s3").setOutputCol("reduced"),
            LogisticRegression().setInputCol("reduced")
                                .setLabelCol("label"),
        ]).fit(frame)
        model.save(model_path, overwrite=True)
        registry = ModelRegistry()  # manifest via env
        registry.load("coldstart_pipeline", model_path)
        engine = ServeEngine(registry, max_batch_rows=max_rows,
                             max_wait_ms=2.0)
        report = engine.warmup("coldstart_pipeline")
        engine.predict("coldstart_pipeline", x[:32])
        engine.shutdown()
        result = {
            "mode": mode,
            "platform": device.platform,
            "device_kind": str(device.device_kind),
            "warmed_buckets": sorted(report["buckets"]),
            "features": n_features,
            "k": k,
        }
    else:
        # Both arms pay jax backend init, eager-dispatch warm-in, and
        # the manifest's model load identically and OUTSIDE the
        # measured window: serve_cold_start_ms is the COMPILE tax this
        # tier removes — engine build → warm-manifest replay → first
        # served request. (Manifest model recovery is PR 6's measured
        # cost; the eager pre-touch mirrors any process that did
        # anything at all with jax before serving.)
        jnp.asarray(np.zeros((4, 4))).astype(jnp.float32)
        (jnp.zeros((4, 4), jnp.float32)
         @ jnp.zeros((4, 4), jnp.float32)).block_until_ready()
        t_rec = time.perf_counter()
        registry = ModelRegistry()  # manifest via env → recovery
        recovery_ms = (time.perf_counter() - t_rec) * 1000.0
        t0 = time.perf_counter()
        engine = ServeEngine(registry, max_batch_rows=max_rows,
                             max_wait_ms=2.0)
        warm_report = engine.warm_from_manifest()
        t_warm = time.perf_counter()
        engine.predict("coldstart_pipeline", x[:32])
        t_first = time.perf_counter()
        compiles = _fresh_compiles()
        cache = get_executable_cache()
        cache_stats = cache.stats() if cache is not None else {}
        engine.shutdown()
        if warm_report["failed"] or not warm_report["warmed"]:
            sys.stderr.write(
                f"warm_from_manifest failed: {warm_report}\n")
            return 1
        result = {
            "mode": mode,
            "platform": device.platform,
            "device_kind": str(device.device_kind),
            "cold_start_ms": (t_first - t0) * 1000.0,
            "warmup_ms": (t_warm - t0) * 1000.0,
            "first_request_ms": (t_first - t_warm) * 1000.0,
            "recovery_ms": recovery_ms,
            "fresh_compiles": compiles,
            "warmed_buckets": sorted(
                int(b) for _n, _v, bk in registry.warm_entries()
                for b in bk),
            "cache_entries": cache_stats.get("entries"),
            "cache_hits": cache_stats.get("hit"),
            "features": n_features,
            "k": k,
        }
    sys.stdout.write(COLDSTART_CHILD_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0


def scenario_multidevice() -> int:
    """Parent leg: run the closed-loop child at device counts 1/2/4 in
    subprocesses, aggregate into ONE sentinel-judged record. Runs
    before any jax import — device count is fixed at jax init, so the
    parent must never initialize a backend itself."""
    import subprocess

    counts = [int(v) for v in os.environ.get(
        "SPARKML_BENCH_SERVE_DEVICES", "1,2,4").split(",") if v.strip()]
    device_ms = float(os.environ.get("SPARKML_BENCH_SERVE_DEVICE_MS",
                                     "60"))
    results = {}
    for n in counts:
        env = dict(os.environ)
        env["SPARKML_BENCH_SERVE_SCENARIO"] = "_multidevice_child"
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
        env["XLA_FLAGS"] = bench_common.force_device_count_flags(n)
        # the child replicates onto every device it sees
        env.pop("SPARK_RAPIDS_ML_TPU_SERVE_REPLICAS", None)
        bench_common.log(f"bench_serve multidevice: child at "
                         f"{n} device(s)")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=900,
        )
        result = bench_common.prefixed_result(proc.stdout,
                                              CHILD_RESULT_PREFIX)
        if proc.returncode != 0 or result is None:
            bench_common.log(
                f"multidevice child at {n} device(s) FAILED "
                f"(rc={proc.returncode}): {proc.stderr[-2000:]}")
            return 1
        results[n] = result
    base_count, top = counts[0], counts[-1]
    base = results[base_count]["rows_per_sec"]
    speedups = {n: (results[n]["rows_per_sec"] / base if base else 0.0)
                for n in counts}
    # efficiency relative to the MEASURED base count — with the default
    # counts "1,2,4" this is the classic (rps_N / rps_1) / N, but an
    # operator benching only 2,4 gets the honest 2→4 efficiency instead
    # of a silently mislabeled number
    efficiency = (speedups[top] / (top / base_count)
                  if top > base_count else 0.0)
    record = {
        "bench": "serve_multidevice",
        "metric": "serve_multidevice_scaling_efficiency",
        "value": efficiency,
        "unit": (f"scaling efficiency: (rows/sec at {top} devices / "
                 f"rows/sec at {base_count}) / ({top}/{base_count})"),
        "higher_is_better": True,
        "platform": results[top]["platform"],
        "device_kind": results[top]["device_kind"],
        "device_counts": counts,
        "modeled_device_ms": device_ms,
        "requests": results[top]["requests"],
        "rows_per_request": results[top]["rows_per_request"],
        "threads": results[top]["threads"],
        "scaling_efficiency": efficiency,
        "speedup_at_top": speedups[top],
    }
    for n in counts:
        record[f"rows_per_sec_{n}"] = results[n]["rows_per_sec"]
        record[f"p99_ms_{n}"] = results[n]["p99_ms"]
        record[f"replica_split_{n}"] = results[n]["replica_split"]
    bench_common.emit_record(record, include_metrics=False)
    bench_common.log(
        "bench_serve multidevice: " + ", ".join(
            f"{n}dev {results[n]['rows_per_sec']:,.0f} rows/s"
            for n in counts)
        + f" -> speedup {speedups[top]:.2f}x at {top} devices "
          f"(efficiency {efficiency:.2f})")
    return 0


def scenario_multidevice_child(device) -> int:
    """One device count's closed-loop measurement (run in its own
    process — see ``scenario_multidevice``). Emits a machine-readable
    result line instead of a bench record; the parent aggregates."""
    import json

    n_requests = _env_int("SPARKML_BENCH_SERVE_MD_REQUESTS", 128)
    n_features = _env_int("SPARKML_BENCH_SERVE_FEATURES", 32)
    k = _env_int("SPARKML_BENCH_SERVE_K", 8)
    n_threads = _env_int("SPARKML_BENCH_SERVE_THREADS", 16)
    max_rows = _env_int("SPARKML_BENCH_SERVE_MAX_ROWS", 256)
    # full-bucket requests: one request = one batch = one modeled
    # device dispatch, so the measured scaling is the TIER's dispatch
    # concurrency, not a coalescing-density artifact (spreading small
    # requests across N queues thins batches — a real trade-off the
    # engine scenario covers; this scenario isolates the replica win)
    rows_per_request = _env_int("SPARKML_BENCH_SERVE_MD_ROWS", 256)
    device_ms = float(os.environ.get("SPARKML_BENCH_SERVE_DEVICE_MS",
                                     "60"))

    import jax

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.obs import get_registry
    from spark_rapids_ml_tpu.serve import ModelRegistry, ServeEngine
    from spark_rapids_ml_tpu.serve.faults import fault_plane

    n_devices = len(jax.devices())
    rng = np.random.default_rng(7)
    x = rng.normal(size=(4096, n_features))
    model = PCA().setK(k).fit(x)
    registry = ModelRegistry()
    registry.register("bench_md_pca", model)
    engine = ServeEngine(
        registry, max_batch_rows=max_rows, max_wait_ms=2.0,
        max_queue_depth=4 * n_requests,
    )
    engine.warmup("bench_md_pca")
    if device_ms > 0:
        # the modeled per-batch device service time: a latency fault at
        # EVERY replica dispatch (GIL-released sleep) — see the module
        # docstring's CPU-CI honesty note
        fault_plane().inject("bench_md_pca", "latency", count=None,
                             seconds=device_ms / 1000.0)
    starts = [int(rng.integers(0, x.shape[0] - rows_per_request))
              for _ in range(n_requests)]
    latencies, wall = _closed_loop(
        lambda i: engine.predict(
            "bench_md_pca",
            x[starts[i]:starts[i] + rows_per_request]),
        n_requests, n_threads)
    snap = get_registry().snapshot().get(
        "sparkml_serve_replica_batches_total", {"samples": []})
    split = {s["labels"]["device"]: s["value"]
             for s in snap["samples"] if s["value"] > 0}

    def _counter_total(name: str) -> float:
        doc = get_registry().snapshot().get(name, {"samples": []})
        return sum(s["value"] for s in doc["samples"])

    batches = _counter_total("sparkml_serve_batches_total")
    engine.shutdown()
    total_rows = n_requests * rows_per_request
    result = {
        "devices": n_devices,
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "requests": n_requests,
        "rows_per_request": rows_per_request,
        "threads": n_threads,
        "rows_per_sec": total_rows / wall if wall > 0 else 0.0,
        "p99_ms": float(np.percentile(latencies, 99)) * 1000.0,
        "replica_split": split,
        "batches": int(batches),
        "requests_per_batch": (n_requests / batches if batches else 0.0),
        "concentrate": os.environ.get(
            "SPARK_RAPIDS_ML_TPU_SERVE_CONCENTRATE", "1"),
    }
    sys.stdout.write(CHILD_RESULT_PREFIX + json.dumps(result) + "\n")
    sys.stdout.flush()
    return 0


def main() -> int:
    n_requests = _env_int("SPARKML_BENCH_SERVE_REQUESTS", 512)
    n_features = _env_int("SPARKML_BENCH_SERVE_FEATURES", 64)
    k = _env_int("SPARKML_BENCH_SERVE_K", 16)
    n_threads = _env_int("SPARKML_BENCH_SERVE_THREADS", 8)
    max_rows = _env_int("SPARKML_BENCH_SERVE_MAX_ROWS", 512)
    scenario = os.environ.get(
        "SPARKML_BENCH_SERVE_SCENARIO", "engine").strip().lower()

    if scenario == "multidevice":
        # MUST dispatch before the jax import below: the parent spawns
        # per-device-count children and never initializes a backend
        return scenario_multidevice()
    if scenario == "coldstart":
        # same rule: the parent only orchestrates restart children
        return scenario_coldstart()
    if scenario == "coalesce":
        return scenario_coalesce()

    import jax

    if scenario == "pipeline":
        return scenario_pipeline(jax.devices()[0])
    if scenario == "wire":
        return scenario_wire(jax.devices()[0])
    if scenario == "_multidevice_child":
        return scenario_multidevice_child(jax.devices()[0])
    if scenario == "_coldstart_child":
        return scenario_coldstart_child(jax.devices()[0])

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.obs import compile_stats, get_registry
    from spark_rapids_ml_tpu.serve import ModelRegistry, ServeEngine

    device = jax.devices()[0]
    rng = np.random.default_rng(7)
    x = rng.normal(size=(4096, n_features))
    model = PCA().setK(k).fit(x)

    registry = ModelRegistry()
    registry.register("bench_pca", model)
    engine = ServeEngine(
        registry, max_batch_rows=max_rows, max_wait_ms=2.0,
        max_queue_depth=4 * n_requests,
    )
    # engine.warmup also precompiles the pipeline's precision x bucket
    # ladder, so the cold split below measures cache/queue warming, not
    # first-request XLA compiles.
    engine.warmup("bench_pca")
    compiles_before = sum(
        s["compiles"] for s in compile_stats().values()
    )

    # Mixed-size closed-loop traffic: 1..256-row requests from N threads.
    # Sizes AND offsets precomputed — numpy Generators are not thread-safe,
    # and the seed must reproduce exactly for sentinel comparisons.
    sizes = rng.integers(1, 257, size=n_requests).tolist()
    starts = [int(rng.integers(0, x.shape[0] - n)) for n in sizes]
    latencies = np.zeros(n_requests)
    total_rows = int(sum(sizes))

    def one(i: int) -> None:
        n, start = sizes[i], starts[i]
        t0 = time.perf_counter()
        engine.predict("bench_pca", x[start:start + n])
        latencies[i] = time.perf_counter() - t0

    t_run = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(n_threads) as pool:
        list(pool.map(one, range(n_requests)))
    wall = time.perf_counter() - t_run
    # The engine's SloSet saw every request; read the verdict before
    # shutdown so the record carries the run's SLO posture.
    slos = list(engine.slo)
    slo_fast_burn = max(
        (s.burn_rate(300.0) for s in slos), default=0.0)
    slo_budget_remaining = min(
        (s.budget_remaining() for s in slos), default=1.0)
    engine.shutdown()

    compiles_after = sum(
        s["compiles"] for s in compile_stats().values()
    )

    def _counter(name: str) -> float:
        snap = get_registry().snapshot().get(name, {"samples": []})
        return sum(s["value"] for s in snap["samples"])

    batch_rows = _counter("sparkml_serve_batch_rows_total")
    bucket_rows = _counter("sparkml_serve_bucket_rows_total")
    busy_s = _counter("sparkml_serve_device_busy_seconds_total")
    overlap2_s = _counter("sparkml_serve_pipeline_overlap_seconds_total")
    p50, p95, p99 = (float(np.percentile(latencies, q))
                     for q in (50, 95, 99))
    # Cold-vs-steady split: the first ~10% of requests (pool.map submits
    # roughly in index order) carry first-touch costs — pipeline fill,
    # allocator/cache warming — the steady tail should not pay.
    n_cold = max(min(32, n_requests), n_requests // 10)
    p99_cold = float(np.percentile(latencies[:n_cold], 99))
    p99_steady = (float(np.percentile(latencies[n_cold:], 99))
                  if n_requests > n_cold else p99_cold)
    bench_common.emit_record({
        "bench": "serve_engine",
        # metric/value/unit make the record sentinel-judgeable as a
        # scalar (p99 seconds, lower-is-better via the "second" unit
        # heuristic) on top of the per-percentile judging that
        # `percentiles` triggers — without "metric" the sentinel could
        # not judge serve records at all.
        "metric": "serve_engine_latency",
        "value": float(np.percentile(latencies, 99)),
        "unit": "seconds (p99 request latency)",
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "requests": n_requests,
        "threads": n_threads,
        "rows": total_rows,
        "seconds": wall,
        "rows_per_sec": total_rows / wall if wall > 0 else 0.0,
        "p50": p50,
        "p95": p95,
        "p99": p99,
        "p99_ms": p99 * 1000.0,
        "p99_cold": p99_cold,
        "p99_steady": p99_steady,
        "percentiles": {"p50": p50, "p95": p95, "p99": p99},
        "mean_batch_occupancy": (
            batch_rows / bucket_rows if bucket_rows else 0.0
        ),
        "pipeline_overlap_fraction": busy_s / wall if wall > 0 else 0.0,
        "pipeline_overlap2_fraction": (
            overlap2_s / wall if wall > 0 else 0.0
        ),
        "pipeline_depth": engine.pipeline_depth,
        "precision": engine.precision,
        "recompile_count": int(compiles_after - compiles_before),
        "slo_fast_burn_rate": slo_fast_burn,
        "slo_budget_remaining": slo_budget_remaining,
        "batches": int(_counter("sparkml_serve_batches_total")),
        "deadline_expired": int(
            _counter("sparkml_serve_deadline_expired_total")
        ),
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
