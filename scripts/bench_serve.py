#!/usr/bin/env python
"""Serving-engine bench: throughput, tail latency, batch occupancy.

Registers a fitted PCA model, warms its shape buckets, then drives a
fixed count of mixed-size predict requests through the engine from a
thread pool — the closed-loop analogue of real ragged traffic — and
emits ONE ``bench_common.emit_record`` JSON line so
``scripts/perf_sentinel.py`` can judge serving regressions against the
committed history from the next PR onward:

* ``rows_per_sec``            — end-to-end serving throughput;
* ``p50`` / ``p95`` / ``p99`` — request latency seconds (also under
  ``percentiles``, the sentinel's per-percentile judging shape), plus
  ``p99_ms`` (the same tail in milliseconds — the headline number the
  pipeline PR is judged on);
* ``p99_cold`` / ``p99_steady`` — the cold-vs-steady split: tail latency
  over the first ~10% of requests (first-touch compiles, cache warming,
  pipeline fill) vs the steady-state remainder — a warmup regression
  and a hot-path regression stop hiding behind one blended number;
* ``mean_batch_occupancy``    — real rows / bucket rows over the run
  (how well coalescing fills the padded shapes);
* ``pipeline_overlap_fraction`` — union device-busy time ÷ wall time
  (``sparkml_serve_device_busy_seconds_total`` over the run): how much
  of the bench wall-clock had at least one batch in flight. > 0
  whenever the pipelined batcher ran; the deeper companion
  ``pipeline_overlap2_fraction`` (>= 2 batches in flight) is the
  stage/compute overlap the PIPELINE_DEPTH=2 window buys;
* ``pipeline_depth``          — the engine's in-flight window, so a
  history line is attributable to its pipeline posture;
* ``recompile_count``         — distinct-signature compiles during the
  serve phase; steady state must stay at 0 (warmup owns them all);
* ``slo_fast_burn_rate``      — the worst fast-window (5 m) SLO burn rate
  at the end of the run (``obs.slo``; > 14.4 would page);
* ``slo_budget_remaining``    — the worst remaining error-budget fraction
  across the engine's SLOs. The sentinel judges this one
  HIGHER-is-better despite the fraction unit (see
  ``perf_sentinel.higher_is_better``).

Knobs (env): SPARKML_BENCH_SERVE_REQUESTS (default 512),
SPARKML_BENCH_SERVE_FEATURES (64), SPARKML_BENCH_SERVE_K (16),
SPARKML_BENCH_SERVE_THREADS (8), SPARKML_BENCH_SERVE_MAX_ROWS (512),
plus the engine's SPARK_RAPIDS_ML_TPU_SERVE_{PIPELINE_DEPTH,PRECISION}.
"""

from __future__ import annotations

import concurrent.futures
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench_common  # noqa: E402 (scripts/ on path when run directly)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def main() -> int:
    n_requests = _env_int("SPARKML_BENCH_SERVE_REQUESTS", 512)
    n_features = _env_int("SPARKML_BENCH_SERVE_FEATURES", 64)
    k = _env_int("SPARKML_BENCH_SERVE_K", 16)
    n_threads = _env_int("SPARKML_BENCH_SERVE_THREADS", 8)
    max_rows = _env_int("SPARKML_BENCH_SERVE_MAX_ROWS", 512)

    import jax

    from spark_rapids_ml_tpu import PCA
    from spark_rapids_ml_tpu.obs import compile_stats, get_registry
    from spark_rapids_ml_tpu.serve import ModelRegistry, ServeEngine

    device = jax.devices()[0]
    rng = np.random.default_rng(7)
    x = rng.normal(size=(4096, n_features))
    model = PCA().setK(k).fit(x)

    registry = ModelRegistry()
    registry.register("bench_pca", model)
    engine = ServeEngine(
        registry, max_batch_rows=max_rows, max_wait_ms=2.0,
        max_queue_depth=4 * n_requests,
    )
    # engine.warmup also precompiles the pipeline's precision x bucket
    # ladder, so the cold split below measures cache/queue warming, not
    # first-request XLA compiles.
    engine.warmup("bench_pca")
    compiles_before = sum(
        s["compiles"] for s in compile_stats().values()
    )

    # Mixed-size closed-loop traffic: 1..256-row requests from N threads.
    # Sizes AND offsets precomputed — numpy Generators are not thread-safe,
    # and the seed must reproduce exactly for sentinel comparisons.
    sizes = rng.integers(1, 257, size=n_requests).tolist()
    starts = [int(rng.integers(0, x.shape[0] - n)) for n in sizes]
    latencies = np.zeros(n_requests)
    total_rows = int(sum(sizes))

    def one(i: int) -> None:
        n, start = sizes[i], starts[i]
        t0 = time.perf_counter()
        engine.predict("bench_pca", x[start:start + n])
        latencies[i] = time.perf_counter() - t0

    t_run = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(n_threads) as pool:
        list(pool.map(one, range(n_requests)))
    wall = time.perf_counter() - t_run
    # The engine's SloSet saw every request; read the verdict before
    # shutdown so the record carries the run's SLO posture.
    slos = list(engine.slo)
    slo_fast_burn = max(
        (s.burn_rate(300.0) for s in slos), default=0.0)
    slo_budget_remaining = min(
        (s.budget_remaining() for s in slos), default=1.0)
    engine.shutdown()

    compiles_after = sum(
        s["compiles"] for s in compile_stats().values()
    )

    def _counter(name: str) -> float:
        snap = get_registry().snapshot().get(name, {"samples": []})
        return sum(s["value"] for s in snap["samples"])

    batch_rows = _counter("sparkml_serve_batch_rows_total")
    bucket_rows = _counter("sparkml_serve_bucket_rows_total")
    busy_s = _counter("sparkml_serve_device_busy_seconds_total")
    overlap2_s = _counter("sparkml_serve_pipeline_overlap_seconds_total")
    p50, p95, p99 = (float(np.percentile(latencies, q))
                     for q in (50, 95, 99))
    # Cold-vs-steady split: the first ~10% of requests (pool.map submits
    # roughly in index order) carry first-touch costs — pipeline fill,
    # allocator/cache warming — the steady tail should not pay.
    n_cold = max(min(32, n_requests), n_requests // 10)
    p99_cold = float(np.percentile(latencies[:n_cold], 99))
    p99_steady = (float(np.percentile(latencies[n_cold:], 99))
                  if n_requests > n_cold else p99_cold)
    bench_common.emit_record({
        "bench": "serve_engine",
        # metric/value/unit make the record sentinel-judgeable as a
        # scalar (p99 seconds, lower-is-better via the "second" unit
        # heuristic) on top of the per-percentile judging that
        # `percentiles` triggers — without "metric" the sentinel could
        # not judge serve records at all.
        "metric": "serve_engine_latency",
        "value": float(np.percentile(latencies, 99)),
        "unit": "seconds (p99 request latency)",
        "platform": device.platform,
        "device_kind": str(device.device_kind),
        "requests": n_requests,
        "threads": n_threads,
        "rows": total_rows,
        "seconds": wall,
        "rows_per_sec": total_rows / wall if wall > 0 else 0.0,
        "p50": p50,
        "p95": p95,
        "p99": p99,
        "p99_ms": p99 * 1000.0,
        "p99_cold": p99_cold,
        "p99_steady": p99_steady,
        "percentiles": {"p50": p50, "p95": p95, "p99": p99},
        "mean_batch_occupancy": (
            batch_rows / bucket_rows if bucket_rows else 0.0
        ),
        "pipeline_overlap_fraction": busy_s / wall if wall > 0 else 0.0,
        "pipeline_overlap2_fraction": (
            overlap2_s / wall if wall > 0 else 0.0
        ),
        "pipeline_depth": engine.pipeline_depth,
        "precision": engine.precision,
        "recompile_count": int(compiles_after - compiles_before),
        "slo_fast_burn_rate": slo_fast_burn,
        "slo_budget_remaining": slo_budget_remaining,
        "batches": int(_counter("sparkml_serve_batches_total")),
        "deadline_expired": int(
            _counter("sparkml_serve_deadline_expired_total")
        ),
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
