"""Gram-accumulator arm sweep on the live chip (VERDICT r3 #10).

Sweeps the Pallas symmetric folded-grid kernel over (block_n, block_r)
shapes and MXU precision arms against the steady-state donated-accumulator
workload bench.py times (65536×4096 f32 batches), plus the XLA
``dot_general`` reference arm. Prints one JSON line per arm and a final
summary line naming the winner — committed records decide whether the
production constants (_BLOCK_N/_BLOCK_R, bfloat16_3x) move.

Precision arms: ``bfloat16_3x`` (production: 2-limb split, 3 MXU passes,
~f32 covariance), ``default`` (single bf16 pass — the throughput ceiling,
~3× fewer MXU passes at bf16 accuracy; recorded to quantify the
speed/precision trade users opt into via TPUML_GRAM_PRECISION).

Run via a patient context (scripts/archive/bench_r04.sh) — never under a killable
timeout against the chip tunnel.
"""

from __future__ import annotations

import json
import os
import time

from bench_common import emit_record

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.utils.platform import (
        PEAK_FLOPS_BF16,
        force_cpu_if_requested,
    )

    force_cpu_if_requested()
    device = jax.devices()[0]
    platform = device.platform
    if platform == "cpu":
        print(json.dumps({
            "metric": "gram sweep", "value": None,
            "note": "pallas TPU kernel: no cpu arm; run on the chip",
        }))
        return

    from spark_rapids_ml_tpu.ops.pallas_gram import fused_centered_gram

    rows = int(os.environ.get("GSWEEP_ROWS", 65536))
    cols = int(os.environ.get("GSWEEP_COLS", 4096))
    steps = int(os.environ.get("GSWEEP_STEPS", 24))
    key = jax.random.PRNGKey(0)
    col_scale = (1.0 + jnp.arange(cols, dtype=jnp.float32)) ** -0.5
    x = jax.device_put(
        jax.random.normal(key, (rows, cols), dtype=jnp.float32)
        * col_scale[None, :],
        device,
    )
    zero_mean = jnp.zeros((cols,), dtype=jnp.float32)
    ones = jnp.ones((rows,), dtype=jnp.float32)
    peak = PEAK_FLOPS_BF16.get(
        str(getattr(device, "device_kind", platform))
    )

    shapes = [(512, 1024), (512, 2048), (1024, 1024), (1024, 2048),
              (256, 1024), (512, 512)]
    precisions = ["bfloat16_3x", "default"]
    results = []

    def record(name, rate, extra=None):
        useful = 2.0 * rows * cols * cols  # full-Gram useful FLOPs
        rec = {
            "metric": f"gram accumulate rows/sec ({rows}x{cols})",
            "arm": name,
            "value": rate,
            "unit": "rows/sec",
            "platform": platform,
            "mfu": (round(useful * rate / rows / peak, 4)
                    if peak else None),
        }
        if extra:
            rec.update(extra)
        results.append(rec)
        emit_record(rec, include_metrics=False)

    def time_arm(fn):
        acc = jnp.zeros((cols, cols), dtype=jnp.float32)
        acc = acc + fn()  # compile
        float(np.asarray(acc[0, 0]))  # fence (host read)
        acc = jnp.zeros((cols, cols), dtype=jnp.float32)
        t0 = time.perf_counter()
        for _ in range(steps):
            acc = acc + fn()
        float(np.asarray(acc[0, 0]))
        return round(steps * rows / (time.perf_counter() - t0), 1)

    for bn, br in shapes:
        for prec in precisions:
            name = f"pallas_{bn}x{br}_{prec}"
            try:
                rate = time_arm(lambda: fused_centered_gram(
                    x, zero_mean, ones, precision=prec,
                    block_n=bn, block_r=br,
                ))
            except Exception as exc:  # noqa: BLE001 - arm must not kill sweep
                print(json.dumps({
                    "arm": name, "error": f"{type(exc).__name__}: {exc}"[:300]
                }), flush=True)
                continue
            record(name, rate)

    # XLA reference arms
    for prec_name, prec in (
        ("bf16_3x", jax.lax.Precision.HIGH),
        ("bf16", jax.lax.Precision.DEFAULT),
    ):
        def xla_gram(p=prec):
            return jax.lax.dot_general(
                x, x, (((0,), (0,)), ((), ())), precision=p,
                preferred_element_type=jnp.float32,
            )

        record(f"xla_dot_general_{prec_name}", time_arm(xla_gram))

    # Winners are per-PRECISION: arms at different precisions do different
    # MXU work (default = 1 bf16 pass, bfloat16_3x = 3), so a global max
    # would always name a single-pass arm and say nothing about the
    # question the sweep decides — which block shape the production
    # bfloat16_3x constants (_BLOCK_N/_BLOCK_R) should carry.
    for prec in ("bfloat16_3x", "default"):
        arms = [r for r in results if r["arm"].endswith(prec)
                or (prec == "bfloat16_3x" and r["arm"].endswith("bf16_3x"))
                or (prec == "default" and r["arm"].endswith("_bf16"))]
        if not arms:
            continue
        best = max(arms, key=lambda r: r["value"])
        emit_record({
            "metric": f"gram sweep winner ({prec})",
            "decides": ("production _BLOCK_N/_BLOCK_R"
                        if prec == "bfloat16_3x"
                        else "single-pass bf16 ceiling (opt-in precision)"),
            "arm": best["arm"],
            "value": best["value"],
            "unit": "rows/sec",
            "mfu": best["mfu"],
            "rows": rows, "cols": cols, "steps": steps,
        })


if __name__ == "__main__":
    main()
